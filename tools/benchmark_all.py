"""Multi-model throughput table — the README FPS column, TPU-native
(the reference reports its FPS in README.md:133-203, produced by its
tools/test_speed.py on RTX 2080 at 1024x512 bs1).

Forward mode measures jit'd inference imgs/sec/chip; --train measures the
full compiled train step (forward+loss+backward+optimizer+EMA) on synthetic
data. Dispatch through the axon tunnel is fenced the same way as bench.py:
calls are queued in blocks and completion is forced by a device-side scalar
readback. Every timed region is armed with the recompile guard
(rtseg_tpu/analysis/recompile.py via fenced_throughput): a benchmark number
can never come from a block that silently paid for an XLA retrace.

    python tools/benchmark_all.py --models fastscnn,bisenetv2,ddrnet
    python tools/benchmark_all.py --train --models bisenetv2
    python tools/benchmark_all.py --eval --batch 8 --imgh 1024 --imgw 2048
    python tools/benchmark_all.py --quant int8 --models fastscnn --batch 4

--quant int8 benches the segquant serving program (per-channel int8
weights, dequant in graph — rtseg_tpu/quant/) next to the f32 one:
fenced imgs/sec, serialized artifact bytes, and argmax agreement side by
side. The committed segquant_cpu.log comes from this mode.
"""

import argparse
import json
import sys
import time
from os import path

sys.path.append(path.dirname(path.dirname(path.abspath(__file__))))

import numpy as np

from rtseg_tpu.utils.bench import REFERENCE_FPS, fenced_throughput

DEFAULT_MODELS = 'fastscnn,bisenetv2,ddrnet,stdc,ppliteseg,enet'

# Per-chip bf16 peaks by device kind (public TPU specs). MFU is computed
# against the bf16 peak of the *detected* device; unknown kinds need
# --peak-flops or MFU is omitted rather than silently wrong.
PEAK_BF16_BY_KIND = {
    'TPU v4 lite': 138e12,  # v4i
    'TPU v4': 275e12,
    'TPU v5 lite': 197e12,
    'TPU v5e': 197e12,
    'TPU v5p': 459e12,
    'TPU v5': 459e12,       # v5p reports plain 'TPU v5'
    'TPU v6 lite': 918e12,  # v6e / Trillium
    'TPU v6e': 918e12,
}

# every bench path below fixes the program dtype to this (SegConfig
# compute_dtype + input casts); peak_flops halves the denominator if it is
# ever switched to float32
BENCH_COMPUTE_DTYPE = 'bfloat16'


def peak_flops(override=None, compute_dtype=BENCH_COMPUTE_DTYPE):
    """(peak FLOP/s for the MFU denominator, device kind), peak from the
    detected device kind (halved for fp32 programs, which run the MXU at
    half rate); peak is None when the kind is unknown and no --peak-flops
    override is given."""
    import jax
    kind = jax.devices()[0].device_kind
    if override:
        return override, kind
    # longest-prefix match so 'TPU v4 lite' never falls into 'TPU v4'
    peak = None
    for k in sorted(PEAK_BF16_BY_KIND, key=len, reverse=True):
        if kind.lower().startswith(k.lower()):
            peak = PEAK_BF16_BY_KIND[k]
            break
    if peak is None:
        print(f'# unknown device kind {kind!r}: pass --peak-flops to get '
              f'MFU', flush=True)
        return None, kind
    if compute_dtype == 'float32':
        peak /= 2
    return peak, kind


def compiled_costs(compiled) -> tuple:
    """(FLOPs, bytes accessed) of a compiled program per XLA's own cost
    analysis (same source as tools/get_model_infos.py); zeros when
    unavailable. The list/tuple unwrap tracks a cost_analysis return-shape
    change across JAX versions."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        if not cost:
            return 0.0, 0.0
        return (float(cost.get('flops', 0.0)),
                float(cost.get('bytes accessed', 0.0)))
    except Exception:
        return 0.0, 0.0


def _compiled_flops(compiled) -> float:
    return compiled_costs(compiled)[0]


#: --warm: a segwarm ExeCache every benched compile goes through (first
#: run stores, later runs deserialize); None = cold, fresh XLA compiles.
#: Either way the first-call compile is timed separately and labeled — a
#: throughput table never silently absorbs (or silently skips) startup.
WARM_CACHE = {'cache': None}


def timed_compile(lowered, name, pins=None):
    """(compiled, first-call compile seconds, label) through the --warm
    cache when set (see rtseg_tpu.warm.timed_compile for the labels)."""
    from rtseg_tpu.warm import timed_compile as warm_timed_compile
    return warm_timed_compile(lowered, name, cache=WARM_CACHE['cache'],
                              pins=pins)


BENCH_S2D = {'on': False,        # set by --s2d; threaded via SegConfig
             'detail_remat': False,
             'hires_remat': False,
             'segnet_pack': False,
             'pack_fullres': False,
             'pallas_cm': None,   # None = production auto (kernel on TPU)
             'fused_head': None}  # None = production auto (fused on TPU)


def bench_forward(name, batch, h, w, queue, trials):
    import jax
    import jax.numpy as jnp
    from rtseg_tpu.config import SegConfig
    from rtseg_tpu.models import get_model

    cfg = SegConfig(dataset='synthetic', model=name, num_class=19,
                    compute_dtype=BENCH_COMPUTE_DTYPE,
                    s2d_stem=BENCH_S2D['on'],
                    segnet_pack=BENCH_S2D['segnet_pack'],
                    save_dir='/tmp/rtseg_bench')
    cfg.resolve(num_devices=1)
    model = get_model(cfg)
    images = jax.device_put(
        np.random.RandomState(0).rand(batch, h, w, 3).astype(np.float32)
    ).astype(jnp.dtype(BENCH_COMPUTE_DTYPE))
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, h, w, 3)), False)

    @jax.jit
    def fwd(variables, images):
        return model.apply(variables, images, False).astype(jnp.float32).sum()

    # one AOT compile serves both the FLOPs readout and the timed calls
    from rtseg_tpu.warm import make_pins
    compiled, compile_s, compile_label = timed_compile(
        fwd.lower(variables, images), f'{name} fwd bs{batch}',
        pins=make_pins(bn_axis=None, s2d_stem=BENCH_S2D['on'],
                       defer_upsample=False))
    flops = _compiled_flops(compiled)
    ips = fenced_throughput(lambda: compiled(variables, images), float,
                            batch, queue=queue, trials=trials,
                            guard_jitted=fwd,
                            guard_name=f'{name} forward bench')
    return ips, flops / batch, compile_s, compile_label


def bench_forward_quant(name, batch, h, w, queue, trials):
    """--quant int8: fenced throughput of the f32 serving program vs the
    segquant int8 program (per-channel weights dequantized in-graph,
    rtseg_tpu/quant/ptq.py), same argmax head for both, plus the
    serialized jax.export artifact bytes and the argmax agreement
    fraction on the bench batch — the three numbers segquant_cpu.log and
    BENCHMARKS.md "Quantized inference methodology" quote side by side."""
    import jax
    import jax.numpy as jnp
    from rtseg_tpu.config import SegConfig
    from rtseg_tpu.export import build_inference_fn
    from rtseg_tpu.models import get_model
    from rtseg_tpu.quant import (build_quantized_inference_fn,
                                 quantize_variables)

    cfg = SegConfig(dataset='synthetic', model=name, num_class=19,
                    compute_dtype=BENCH_COMPUTE_DTYPE,
                    s2d_stem=BENCH_S2D['on'],
                    segnet_pack=BENCH_S2D['segnet_pack'],
                    save_dir='/tmp/rtseg_bench')
    cfg.resolve(num_devices=1)
    model = get_model(cfg)
    images = jax.device_put(
        np.random.RandomState(0).rand(batch, h, w, 3).astype(np.float32))
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, h, w, 3)), False)
    qvariables = quantize_variables(variables)

    out = {}
    preds = {}
    spec = jax.ShapeDtypeStruct((batch, h, w, 3), jnp.float32)
    arms = (('f32', build_inference_fn(model, variables,
                                       BENCH_COMPUTE_DTYPE, argmax=True)),
            ('int8', build_quantized_inference_fn(model, qvariables,
                                                  BENCH_COMPUTE_DTYPE,
                                                  argmax=True)))
    for arm, fn in arms:
        jitted = jax.jit(fn)
        compiled, compile_s, compile_label = timed_compile(
            jitted.lower(images), f'{name} {arm} serve bs{batch}')
        flops = _compiled_flops(compiled)
        ips = fenced_throughput(lambda _c=compiled: _c(images),
                                lambda o: int(o[0, 0, 0]), batch,
                                queue=queue, trials=trials,
                                guard_jitted=jitted,
                                guard_name=f'{name} {arm} serve bench')
        # the bytes the registry would ship: the same jax.export
        # serialization `segship bake` writes per bucket
        art_bytes = len(jax.export.export(jax.jit(fn))(spec).serialize())
        preds[arm] = np.asarray(compiled(images))
        out[arm] = {'ips': ips, 'flops_per_img': flops / batch,
                    'compile_s': compile_s,
                    'compile_label': compile_label,
                    'artifact_bytes': art_bytes}
    out['agreement_frac'] = float((preds['f32'] == preds['int8']).mean())
    return out


def _setup_state(name, batch, h, w, **cfg_overrides):
    """Shared train/eval-step harness: config, model, 1-device mesh, train
    state, and a synthetic device-resident batch."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from rtseg_tpu.config import SegConfig
    from rtseg_tpu.models import get_model
    from rtseg_tpu.parallel.mesh import DATA_AXIS
    from rtseg_tpu.train.optim import get_optimizer
    from rtseg_tpu.train.state import create_train_state

    cfg = SegConfig(dataset='synthetic', model=name, num_class=19,
                    compute_dtype=BENCH_COMPUTE_DTYPE,
                    s2d_stem=BENCH_S2D['on'],
                    segnet_pack=BENCH_S2D['segnet_pack'],
                    detail_remat=BENCH_S2D['detail_remat'],
                    hires_remat=BENCH_S2D['hires_remat'],
                    pack_fullres=BENCH_S2D['pack_fullres'],
                    use_pallas_metrics=BENCH_S2D['pallas_cm'],
                    fused_head=BENCH_S2D['fused_head'],
                    save_dir='/tmp/rtseg_bench', **cfg_overrides)
    cfg.resolve(num_devices=1)
    cfg.resolve_schedule(train_num=batch * 1000)
    model = get_model(cfg)
    opt = get_optimizer(cfg)
    mesh = Mesh(np.array(jax.devices()[:1]), (DATA_AXIS,))
    state = create_train_state(model, opt, jax.random.PRNGKey(0),
                               jnp.zeros((1, h, w, 3), jnp.float32))
    rng = np.random.RandomState(0)
    images = jax.device_put(rng.rand(batch, h, w, 3).astype(np.float32))
    masks = jax.device_put(
        rng.randint(0, 19, (batch, h, w)).astype(np.int32))
    return cfg, model, opt, mesh, state, images, masks


def bench_eval(name, batch, h, w, queue, trials):
    """Validation-step throughput: EMA-weights forward + on-device
    confusion matrix (the per-batch work of SegTrainer.validate)."""
    import jax
    from rtseg_tpu.train.step import build_eval_step

    # use_ema=True so the benchmarked config states what it exercises (the
    # EMA slots mirror params at init either way, but the claim should not
    # depend on that invariant)
    cfg, model, _, mesh, state, images, masks = _setup_state(
        name, batch, h, w, use_ema=True)
    eval_step = build_eval_step(cfg, model, mesh)
    eval_step.pin()
    from rtseg_tpu.warm.prime import step_pins
    compiled, compile_s, compile_label = timed_compile(
        eval_step.jitted.lower(jax.device_get(state), images, masks),
        f'{name} eval bs{batch}', pins=step_pins(eval_step))
    flops = _compiled_flops(compiled)
    ips = fenced_throughput(lambda: compiled(state, images, masks)[0, 0],
                            float, batch, queue=queue, trials=trials,
                            guard_jitted=eval_step.jitted,
                            guard_name=f'{name} eval bench')
    return ips, flops / batch, compile_s, compile_label


def bench_train(name, batch, h, w, queue, trials):
    import jax
    from rtseg_tpu.models.registry import AUX_MODELS, DETAIL_HEAD_MODELS
    from rtseg_tpu.train.step import build_train_step

    cfg, model, opt, mesh, state, images, masks = _setup_state(
        name, batch, h, w, train_bs=batch,
        use_aux=name in AUX_MODELS,
        use_detail_head=name in DETAIL_HEAD_MODELS,
        use_ema=True, loss_type='ohem')
    step = build_train_step(cfg, model, opt, mesh)

    step.pin()
    from rtseg_tpu.warm.prime import step_pins
    compiled, compile_s, compile_label = timed_compile(
        step.jitted.lower(jax.device_get(state), images, masks),
        f'{name} train bs{batch}', pins=step_pins(step))
    flops = _compiled_flops(compiled)

    carry = {'state': state}

    def call():
        carry['state'], metrics = compiled(carry['state'], images, masks)
        return metrics['loss']

    ips = fenced_throughput(call, float, batch, queue=queue, trials=trials,
                            warmup=1, guard_jitted=step.jitted,
                            guard_name=f'{name} train bench')
    return ips, flops / batch, compile_s, compile_label


def _make_png_dataset(root, n, h, w, seed=0):
    """Synthesize a Custom-layout PNG dataset (real decode cost) for the
    offline loader benchmark."""
    import os
    from PIL import Image
    rng = np.random.RandomState(seed)
    for mode, k in (('train', n), ('val', max(2, n // 8))):
        os.makedirs(os.path.join(root, mode, 'imgs'), exist_ok=True)
        os.makedirs(os.path.join(root, mode, 'masks'), exist_ok=True)
        for i in range(k):
            Image.fromarray(rng.randint(0, 255, (h, w, 3), dtype=np.uint8)
                            ).save(os.path.join(root, mode, 'imgs',
                                                f'{i:04d}.png'))
            Image.fromarray(rng.randint(0, 5, (h, w), dtype=np.uint8)
                            ).save(os.path.join(root, mode, 'masks',
                                                f'{i:04d}.png'))
    with open(os.path.join(root, 'data.yaml'), 'w') as f:
        f.write(f'path: {root}\nnames:\n'
                + ''.join(f'  {i}: c{i}\n' for i in range(5)))


def bench_data(args, sink) -> int:
    """Offline loader throughput: imgs/sec through the full batch-
    production path (fetch + augment + stack), decode path vs segpipe
    packed cache, no device work. The numbers BENCHMARKS.md "Loader
    throughput methodology" and segpipe_cpu.log commit come from here."""
    import tempfile
    import time
    from rtseg_tpu.config import SegConfig
    from rtseg_tpu.data import get_dataset
    from rtseg_tpu.data.loader import ShardedLoader
    from rtseg_tpu.data.segpipe import open_or_build

    work = args.data_root or tempfile.mkdtemp(prefix='segpipe_bench_')
    if args.data_root is None:
        print(f'# generating {args.data_samples} {args.imgw}x{args.imgh} '
              f'PNGs under {work}', flush=True)
        _make_png_dataset(work, args.data_samples, args.imgh, args.imgw)
    cfg = SegConfig(dataset=args.data_dataset, data_root=work, num_class=5,
                    crop_size=min(args.imgh, args.imgw) // 2,
                    train_size=args.imgh if args.data_dataset == 'custom'
                    else None,
                    h_flip=0.5, randscale=0.1,
                    save_dir=tempfile.mkdtemp(prefix='segpipe_bench_save_'))
    cfg.resolve(num_devices=1)
    train_ds, _ = get_dataset(cfg)

    def run(cache, tag):
        loader = ShardedLoader(
            train_ds, global_batch=min(args.batch, len(train_ds)), seed=0,
            shuffle=True, drop_last=True, cache=cache,
            mp_workers=args.aug_workers, tag=tag,
            workers=0 if args.aug_workers else 4)
        imgs = 0
        t0 = time.perf_counter()
        for ep in range(args.data_epochs):
            loader.set_epoch(ep)
            for batch in loader:
                imgs += len(batch[0])
        dur = time.perf_counter() - t0
        return imgs / dur, imgs

    decode_ips, n_imgs = run(None, 'decode')
    t0 = time.perf_counter()
    cache = open_or_build(train_ds, cfg.cache_dir)
    build_s = time.perf_counter() - t0
    cached_ips, _ = run(cache, 'cached')
    for tag, ips in (('decode', decode_ips), ('cached', cached_ips)):
        print(json.dumps({
            'metric': f'loader {tag} imgs/sec '
                      f'({args.imgw}x{args.imgh} PNG, bs{args.batch}, '
                      f'{args.aug_workers} aug workers)',
            'value': round(ips, 1), 'unit': 'imgs/sec'}), flush=True)
        if sink is not None:
            sink.emit({'event': 'bench_result', 'mode': 'data',
                       'path': tag, 'imgs_per_sec': round(ips, 2),
                       'imgs': n_imgs, 'batch': args.batch,
                       'imgh': args.imgh, 'imgw': args.imgw,
                       'aug_workers': args.aug_workers,
                       'cache_build_s': round(build_s, 3)})
    print(f'\n| path | loader imgs/sec (offline, bs{args.batch}, '
          f'{args.data_epochs} epochs) |')
    print('|---|---|')
    print(f'| decode | {decode_ips:.1f} |')
    print(f'| segpipe cache | {cached_ips:.1f} |')
    print(f'\ncache build: {build_s:.2f}s one-time '
          f'({build_s * decode_ips / max(n_imgs // args.data_epochs, 1):.2f} '
          f'decode-epochs equivalent) | speedup {cached_ips / decode_ips:.2f}x')
    return 0


def bench_quant_sweep(args, device_kind, sink) -> int:
    """--quant int8 sweep: one side-by-side row per model."""
    rows = []
    for name in [m.strip() for m in args.models.split(',') if m.strip()]:
        try:
            r = bench_forward_quant(name, args.batch, args.imgh,
                                    args.imgw, args.queue, args.trials)
        except Exception as e:          # keep the sweep going
            print(f'| {name} | FAILED: {type(e).__name__}: {e} |',
                  flush=True)
            continue
        for arm in ('f32', 'int8'):
            print(f'# {name} {arm} first-call compile: '
                  f'{r[arm]["compile_s"]:.2f} s '
                  f'({r[arm]["compile_label"]})', flush=True)
        rows.append((name, r))
        print(json.dumps({
            'metric': f'{name} quant-serve imgs/sec/chip '
                      f'({args.imgw}x{args.imgh}, bs{args.batch})',
            'f32_imgs_per_sec': round(r['f32']['ips'], 1),
            'int8_imgs_per_sec': round(r['int8']['ips'], 1),
            'f32_artifact_bytes': r['f32']['artifact_bytes'],
            'int8_artifact_bytes': r['int8']['artifact_bytes'],
            'agreement_frac': round(r['agreement_frac'], 4),
        }), flush=True)
        if sink is not None:
            sink.emit({'event': 'bench_result', 'model': name,
                       'mode': 'quant-serve', 'batch': args.batch,
                       'imgh': args.imgh, 'imgw': args.imgw,
                       'device_kind': device_kind,
                       'f32_imgs_per_sec': round(r['f32']['ips'], 2),
                       'int8_imgs_per_sec': round(r['int8']['ips'], 2),
                       'f32_artifact_bytes': r['f32']['artifact_bytes'],
                       'int8_artifact_bytes': r['int8']['artifact_bytes'],
                       'agreement_frac': round(r['agreement_frac'], 4)})
    print(f'\n| model | f32 imgs/sec ({device_kind}, bs{args.batch}) | '
          f'int8 imgs/sec | int8/f32 | f32 artifact | int8 artifact | '
          f'shrink | agreement |')
    print('|---|---|---|---|---|---|---|---|')
    for name, r in rows:
        f32b, i8b = r['f32']['artifact_bytes'], r['int8']['artifact_bytes']
        print(f'| {name} | {r["f32"]["ips"]:.0f} | '
              f'{r["int8"]["ips"]:.0f} | '
              f'{r["int8"]["ips"] / r["f32"]["ips"]:.2f}x | '
              f'{f32b / 2**20:.2f} MiB | {i8b / 2**20:.2f} MiB | '
              f'{f32b / i8b:.2f}x | {r["agreement_frac"]:.4f} |')
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--models', type=str, default=DEFAULT_MODELS)
    ap.add_argument('--batch', type=int, default=32)
    ap.add_argument('--imgh', type=int, default=512)
    ap.add_argument('--imgw', type=int, default=1024)
    ap.add_argument('--queue', type=int, default=20)
    ap.add_argument('--trials', type=int, default=3)
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument('--train', action='store_true',
                      help='benchmark the full train step instead of '
                           'inference')
    mode.add_argument('--eval', action='store_true',
                      help='benchmark the validation step (EMA forward + '
                           'on-device confusion matrix)')
    mode.add_argument('--data', action='store_true',
                      help='offline input-pipeline throughput (imgs/sec '
                           'through batch production, no device): decode '
                           'path vs segpipe packed cache')
    ap.add_argument('--data-root', default=None,
                    help='--data: existing dataset root (default: '
                         'synthesize a PNG dataset in a temp dir)')
    ap.add_argument('--data-dataset', default='custom',
                    help='--data: dataset type for --data-root')
    ap.add_argument('--data-samples', type=int, default=48,
                    help='--data: synthesized PNG count')
    ap.add_argument('--data-epochs', type=int, default=3,
                    help='--data: epochs per timed pass')
    ap.add_argument('--aug-workers', type=int, default=0,
                    help='--data: segpipe multi-process augment workers '
                         '(0 = thread pool)')
    ap.add_argument('--s2d', action='store_true',
                    help='enable s2d_stem input packing (config.s2d_stem)')
    ap.add_argument('--detail-remat', action='store_true',
                    help='bisenetv2: rematerialize the DetailBranch in '
                         'backward (frees HBM for larger train batches)')
    ap.add_argument('--segnet-pack', action='store_true',
                    help='enable segnet full-res S2D layout '
                         '(config.segnet_pack; the bs64 OOM mitigation)')
    ap.add_argument('--pack-fullres', action='store_true',
                    help='bisenetv2: eval-only S2D(2) layout for the '
                         'full-res stem/detail stages '
                         '(config.pack_fullres)')
    ap.add_argument('--hires-remat', action='store_true',
                    help='stdc/ddrnet/ppliteseg: rematerialize the '
                         'high-resolution encoder stages in backward '
                         '(config.hires_remat)')
    ap.add_argument('--pallas-cm', action='store_true', default=None,
                    help='eval mode: force the blocked Pallas confusion-'
                         'matrix kernel (config.use_pallas_metrics); '
                         'default None follows production auto (kernel '
                         'on TPU)')
    ap.add_argument('--no-pallas-cm', dest='pallas_cm',
                    action='store_false',
                    help='eval mode: force the one-hot-einsum CM (the '
                         'A/B baseline)')
    ap.add_argument('--fused-head', action='store_true', default=None,
                    help='eval mode: force the fused upsample+argmax '
                         'serving head (config.fused_head); default None '
                         'follows production auto (fused on TPU)')
    ap.add_argument('--no-fused-head', dest='fused_head',
                    action='store_false',
                    help='eval mode: force the materializing '
                         'upsample-then-argmax path (the A/B baseline)')
    ap.add_argument('--quant', choices=('int8',), default=None,
                    help='forward mode: bench the segquant int8 serving '
                         'program next to f32 — fenced imgs/sec, '
                         'serialized artifact bytes, and argmax '
                         'agreement side by side')
    ap.add_argument('--peak-flops', type=float, default=None,
                    help='override the per-chip peak FLOP/s used for MFU '
                         '(required on device kinds not in '
                         'PEAK_BF16_BY_KIND)')
    ap.add_argument('--obs-dir', default=None,
                    help='segscope: write bench_result events (and the '
                         'fenced_throughput block spans) as JSONL under '
                         'this dir, readable by tools/segscope.py')
    warm_mode = ap.add_mutually_exclusive_group()
    warm_mode.add_argument('--cold', action='store_true',
                           help='fresh XLA compile per model (default); '
                                'the first-call compile line is labeled '
                                'cold')
    warm_mode.add_argument('--warm', action='store_true',
                           help='compile through the segwarm executable '
                                'cache at --warm-cache: the first sweep '
                                'stores, repeat sweeps deserialize '
                                '(labeled warm) — so startup numbers are '
                                'honest about which path produced them')
    ap.add_argument('--warm-cache', default='/tmp/rtseg_bench/segwarm',
                    help='--warm: segwarm cache directory')
    args = ap.parse_args()

    if args.warm:
        from rtseg_tpu.warm import ExeCache, enable_compile_cache
        enable_compile_cache(cache_dir=args.warm_cache)
        WARM_CACHE['cache'] = ExeCache.at(args.warm_cache)

    sink = None
    if args.obs_dir:
        from rtseg_tpu import obs
        sink = obs.init_run(args.obs_dir,
                            meta={'tool': 'benchmark_all',
                                  'models': args.models,
                                  'batch': args.batch,
                                  'imgh': args.imgh, 'imgw': args.imgw})
        obs.set_sink(sink)

    if args.data:
        return bench_data(args, sink)

    BENCH_S2D['on'] = args.s2d
    BENCH_S2D['segnet_pack'] = args.segnet_pack
    BENCH_S2D['detail_remat'] = args.detail_remat
    BENCH_S2D['hires_remat'] = args.hires_remat
    BENCH_S2D['pack_fullres'] = args.pack_fullres
    BENCH_S2D['pallas_cm'] = args.pallas_cm
    BENCH_S2D['fused_head'] = args.fused_head
    peak, device_kind = peak_flops(args.peak_flops)
    if args.quant:
        if args.train or args.eval:
            ap.error('--quant benches the serving forward only')
        return bench_quant_sweep(args, device_kind, sink)
    kind = 'train' if args.train else 'eval' if args.eval else 'forward'
    rows = []
    for name in [m.strip() for m in args.models.split(',') if m.strip()]:
        fn = (bench_train if args.train
              else bench_eval if args.eval else bench_forward)
        try:
            ips, flops_per_img, compile_s, compile_label = fn(
                name, args.batch, args.imgh, args.imgw,
                args.queue, args.trials)
        except Exception as e:          # keep the sweep going
            print(f'| {name} | FAILED: {type(e).__name__}: {e} |',
                  flush=True)
            continue
        # first-call compile on its own line, never folded into the
        # post-warmup steady-state imgs/sec
        print(f'# {name} first-call compile: {compile_s:.2f} s '
              f'({compile_label})', flush=True)
        base = REFERENCE_FPS.get(name)
        # model FLOPs x images/sec over the chip's bf16 peak — how much of
        # the MXU the shape actually uses (VERDICT round-1 weak #3)
        mfu = (flops_per_img * ips / peak
               if flops_per_img and peak else None)
        # the reference has no train- or eval-step throughput numbers (its
        # FPS is bare forward at 1024x512), so those ratios would be
        # meaningless — vs_baseline only in forward mode
        comparable = base and not args.train and not args.eval
        ratio = f'{ips / base:.1f}x' if comparable else '—'
        rows.append((name, ips, base, ratio, mfu))
        print(json.dumps({
            'metric': f'{name} {kind} imgs/sec/chip '
                      f'({args.imgw}x{args.imgh}, bs{args.batch})',
            'value': round(ips, 1),
            'unit': 'imgs/sec',
            'vs_baseline': round(ips / base, 3) if comparable else None,
            'mfu': round(mfu, 4) if mfu is not None else None,
            'compile_s': round(compile_s, 3),
            'compile_label': compile_label,
        }), flush=True)
        if sink is not None:
            sink.emit({'event': 'bench_result', 'model': name,
                       'mode': kind, 'imgs_per_sec': round(ips, 2),
                       'batch': args.batch, 'imgh': args.imgh,
                       'imgw': args.imgw, 'device_kind': device_kind,
                       'compile_s': round(compile_s, 3),
                       'compile_label': compile_label,
                       'mfu': round(mfu, 4) if mfu is not None else None})

    print(f'\n| model | {kind} imgs/sec/chip ({device_kind}, '
          f'bs{args.batch}) | ref FPS (RTX 2080, bs1) | speedup | MFU |')
    print('|---|---|---|---|---|')
    for name, ips, base, ratio, mfu in rows:
        mfu_s = f'{100 * mfu:.1f}%' if mfu is not None else '—'
        print(f'| {name} | {ips:.0f} | {base if base else "—"} | {ratio} | '
              f'{mfu_s} |')
    return 0


if __name__ == '__main__':
    sys.exit(main())
