"""Export a model to a portable StableHLO serving artifact.

TPU-native counterpart of the reference's ONNX export path (reference
models/ddrnet.py:55-58, models/stdc.py:90-93): weights are baked into the
graph, the head is int8 argmax (or fp32 logits with --logits).

    python tools/export.py --model ddrnet --num_class 19 \
        --load_ckpt_path save/best.ckpt --out save/ddrnet.stablehlo
"""

import argparse
import sys
from os import path

sys.path.append(path.dirname(path.dirname(path.abspath(__file__))))

from rtseg_tpu.config import SegConfig
from rtseg_tpu.export import export_model, save_exported


def main() -> int:
    # Export is pure lowering: the serving targets come from --platforms,
    # not from the process's runtime backend. Pin the host backend to CPU
    # so exporting works on machines with no (or unreachable) accelerator.
    import jax
    try:
        jax.config.update('jax_platforms', 'cpu')
    except Exception:
        pass
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--model', type=str, default='bisenetv2')
    ap.add_argument('--encoder', type=str, default=None)
    ap.add_argument('--decoder', type=str, default=None)
    ap.add_argument('--num_class', type=int, default=19)
    ap.add_argument('--use_aux', action='store_true',
                    help='model was trained with auxiliary heads (its ckpt '
                         'params include them; needed for restore)')
    ap.add_argument('--use_detail_head', action='store_true',
                    help='STDC detail-head checkpoint')
    ap.add_argument('--compute_dtype', type=str, default='bfloat16',
                    choices=['bfloat16', 'float32'],
                    help='graph compute dtype; use float32 for CPU serving')
    ap.add_argument('--platforms', type=str, default='cpu,tpu',
                    help='comma-separated lowering targets')
    ap.add_argument('--imgh', type=int, default=512)
    ap.add_argument('--imgw', type=int, default=1024)
    ap.add_argument('--batch', type=int, default=1,
                    help='0 exports a symbolic (any-size) batch dimension')
    ap.add_argument('--logits', action='store_true',
                    help='export fp32 logits instead of the int8 argmax head')
    ap.add_argument('--load_ckpt_path', type=str, default=None)
    ap.add_argument('--out', type=str, default=None)
    args = ap.parse_args()

    cfg = SegConfig(dataset='synthetic', model=args.model,
                    num_class=args.num_class,
                    use_aux=args.use_aux,
                    use_detail_head=args.use_detail_head,
                    compute_dtype=args.compute_dtype,
                    save_dir='/tmp/rtseg_export')
    if args.encoder:
        cfg = cfg.replace(encoder=args.encoder)
    if args.decoder:
        cfg = cfg.replace(decoder=args.decoder)
    cfg.resolve(num_devices=1)

    exported = export_model(cfg, imgh=args.imgh, imgw=args.imgw,
                            batch=args.batch or None,
                            argmax=not args.logits,
                            ckpt_path=args.load_ckpt_path,
                            platforms=tuple(
                                p.strip() for p in args.platforms.split(',')
                                if p.strip()))
    out = args.out or f'{cfg.save_dir}/{args.model}.stablehlo'
    out = save_exported(exported, out)
    print(f'exported {args.model} ({args.imgh}x{args.imgw}, '
          f'batch={"poly" if not args.batch else args.batch}, '
          f'head={"logits" if args.logits else "int8 argmax"}) -> {out}')
    return 0


if __name__ == '__main__':
    sys.exit(main())
