"""Model complexity info — equivalent of reference tools/get_model_infos.py:9-27.

Parameter count from the Flax param tree; FLOPs from XLA's own compiled cost
analysis (replaces ptflops), with a param-only fallback mirroring the
reference's numel path.
"""

import sys
from os import path

sys.path.append(path.dirname(path.dirname(path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from rtseg_tpu.config import SegConfig, load_parser
from rtseg_tpu.models import get_model


def cal_model_params(config, imgh=1024, imgw=2048):
    model = get_model(config)
    x = jnp.zeros((1, imgh, imgw, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, False)
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(variables['params']))
    print('\n=========Model Info=========')
    print(f'Model: {config.model}')
    print(f'Parameters: {n_params / 1e6:.2f} M ({n_params})')
    try:
        lowered = jax.jit(
            lambda v, x: model.apply(v, x, False)).lower(variables, x)
        cost = lowered.compile().cost_analysis()
        flops = cost.get('flops') if isinstance(cost, dict) else None
        if flops:
            print(f'Forward FLOPs @ {imgw}x{imgh}: {flops / 1e9:.2f} GFLOPs')
    except Exception as e:                      # cost analysis is best-effort
        print(f'(FLOPs unavailable: {type(e).__name__})')
    return n_params


def cal_train_step_memory(config, imgh=1024, imgw=1024, batch=None):
    """AOT-compile the full train step and report XLA's memory analysis —
    how much temp HBM a (crop, batch, remat) combination needs, without
    running anything. No reference equivalent; sizes TPU training runs."""
    from jax.sharding import Mesh
    from rtseg_tpu.parallel.mesh import DATA_AXIS
    from rtseg_tpu.train.optim import get_optimizer
    from rtseg_tpu.train.state import create_train_state
    from rtseg_tpu.train.step import build_train_step

    batch = batch or config.train_bs
    if config.total_itrs <= 0:
        config.resolve_schedule(train_num=batch * 100)
    model = get_model(config)
    opt = get_optimizer(config)
    mesh = Mesh(np.array(jax.devices()[:1]), (DATA_AXIS,))
    state = create_train_state(model, opt, jax.random.PRNGKey(0),
                               jnp.zeros((1, imgh, imgw, 3), jnp.float32))
    step = build_train_step(config, model, opt, mesh)
    images = jax.ShapeDtypeStruct((batch, imgh, imgw, 3), jnp.float32)
    masks = jax.ShapeDtypeStruct((batch, imgh, imgw), jnp.int32)
    step.pin()
    m = step.jitted.lower(jax.device_get(state), images, masks) \
        .compile().memory_analysis()
    gib = 2.0 ** 30
    print(f'\n=========Train-step memory (XLA) @ {imgw}x{imgh} '
          f'bs{batch} remat={config.remat}=========')
    print(f'temp:   {m.temp_size_in_bytes / gib:.2f} GiB')
    print(f'args:   {m.argument_size_in_bytes / gib:.2f} GiB')
    print(f'output: {m.output_size_in_bytes / gib:.2f} GiB')
    return m


if __name__ == '__main__':
    argv = sys.argv[1:]
    train_mem = '--train_memory' in argv
    if train_mem:
        argv.remove('--train_memory')
        sys.argv = sys.argv[:1] + argv
    config = SegConfig(dataset='synthetic', model='bisenetv2', num_class=19)
    if argv:
        config = load_parser(config)
    config.resolve(num_devices=1)
    if train_mem:
        # memory sizing only — skip the separate FLOPs forward compile
        cal_train_step_memory(config, imgh=config.crop_h, imgw=config.crop_w)
    else:
        cal_model_params(config)
