"""Model complexity info — equivalent of reference tools/get_model_infos.py:9-27.

Parameter count from the Flax param tree; FLOPs from XLA's own compiled cost
analysis (replaces ptflops), with a param-only fallback mirroring the
reference's numel path.
"""

import sys
from os import path

sys.path.append(path.dirname(path.dirname(path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from rtseg_tpu.config import SegConfig, load_parser
from rtseg_tpu.models import get_model


def cal_model_params(config, imgh=1024, imgw=2048):
    model = get_model(config)
    x = jnp.zeros((1, imgh, imgw, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, False)
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree.leaves(variables['params']))
    print('\n=========Model Info=========')
    print(f'Model: {config.model}')
    print(f'Parameters: {n_params / 1e6:.2f} M ({n_params})')
    try:
        lowered = jax.jit(
            lambda v, x: model.apply(v, x, False)).lower(variables, x)
        cost = lowered.compile().cost_analysis()
        flops = cost.get('flops') if isinstance(cost, dict) else None
        if flops:
            print(f'Forward FLOPs @ {imgw}x{imgh}: {flops / 1e9:.2f} GFLOPs')
    except Exception as e:                      # cost analysis is best-effort
        print(f'(FLOPs unavailable: {type(e).__name__})')
    return n_params


if __name__ == '__main__':
    config = SegConfig(dataset='synthetic', model='bisenetv2', num_class=19)
    if len(sys.argv) > 1:
        config = load_parser(config)
    config.resolve(num_devices=1)
    cal_model_params(config)
