"""Import a reference-framework checkpoint (.pth) into an rtseg_tpu ckpt.

One-command migration for users carrying weights trained with
`acai66/realtime-semantic-segmentation-pytorch` (reference
core/base_trainer.py:142-163 save format — {'state_dict': ...}):

    python tools/import_reference.py --model bisenetv2 --num_class 19 \
        --pth reference_best.pth --out save/imported.ckpt

The output is a weights checkpoint in this framework's orbax format
('best'-style: params + batch_stats) that `--load_ckpt_path` accepts for
predict / validate / fine-tune. The state_dict -> Flax mapping is the
call-order transplant machinery (rtseg_tpu/utils/transplant.py), whose
per-architecture correctness is pinned by tests/test_logit_parity.py.
"""

import argparse
import sys
from os import path

sys.path.append(path.dirname(path.dirname(path.abspath(__file__))))


def main() -> int:
    # pure host-side work: no accelerator needed
    import jax
    try:
        jax.config.update('jax_platforms', 'cpu')
    except Exception:
        pass
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--model', type=str, required=True)
    ap.add_argument('--num_class', type=int, required=True)
    ap.add_argument('--use_aux', action='store_true')
    ap.add_argument('--use_detail_head', action='store_true')
    ap.add_argument('--pth', type=str, required=True,
                    help='reference .pth checkpoint')
    ap.add_argument('--out', type=str, required=True,
                    help='output orbax checkpoint directory')
    ap.add_argument('--imgh', type=int, default=64,
                    help='init trace height (any valid size works)')
    ap.add_argument('--imgw', type=int, default=64)
    args = ap.parse_args()
    if args.model == 'smp':
        # the reference's smp family delegates to the external
        # segmentation_models_pytorch library, whose state_dict layout this
        # importer has no call-order mapping for (SD_REORDER covers the 36
        # in-repo architectures); fail clearly instead of deep in get_model
        ap.error("--model smp (reference's segmentation_models_pytorch "
                 'family) is not supported by the importer; only the 36 '
                 'in-repo architectures are.')

    import jax.numpy as jnp
    from rtseg_tpu.config import SegConfig
    from rtseg_tpu.models import get_model
    from rtseg_tpu.train.checkpoint import save_weights_ckpt
    from rtseg_tpu.utils.transplant import load_reference_pth

    cfg = SegConfig(dataset='synthetic', model=args.model,
                    num_class=args.num_class, use_aux=args.use_aux,
                    use_detail_head=args.use_detail_head,
                    save_dir='/tmp/rtseg_import')
    cfg.resolve(num_devices=1)
    model = get_model(cfg)
    variables = load_reference_pth(
        args.pth, args.model, model,
        jnp.zeros((1, args.imgh, args.imgw, 3), jnp.float32))

    out = path.abspath(args.out)
    save_weights_ckpt(out, variables['params'],
                      variables.get('batch_stats', {}),
                      cur_epoch=0, best_score=0.0,
                      imported_from=path.abspath(args.pth))
    n = sum(int(p.size) for p in jax.tree.leaves(variables['params']))
    print(f'Imported {args.pth} -> {out} ({n / 1e6:.2f}M params). '
          f'Use --load_ckpt_path {args.out} for predict/val/fine-tune.')
    return 0


if __name__ == '__main__':
    sys.exit(main())
