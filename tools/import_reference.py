"""Import a reference-framework checkpoint (.pth) into an rtseg_tpu ckpt.

One-command migration for users carrying weights trained with
`acai66/realtime-semantic-segmentation-pytorch` (reference
core/base_trainer.py:142-163 save format — {'state_dict': ...}):

    python tools/import_reference.py --model bisenetv2 --num_class 19 \
        --pth reference_best.pth --out save/imported.ckpt

The output is a weights checkpoint in this framework's orbax format
('best'-style: params + batch_stats) that `--load_ckpt_path` accepts for
predict / validate / fine-tune. The state_dict -> Flax mapping is the
call-order transplant machinery (rtseg_tpu/utils/transplant.py), whose
per-architecture correctness is pinned by tests/test_logit_parity.py.
"""

import argparse
import sys
from os import path

sys.path.append(path.dirname(path.dirname(path.abspath(__file__))))


def main() -> int:
    # pure host-side work: no accelerator needed
    import jax
    try:
        jax.config.update('jax_platforms', 'cpu')
    except Exception:
        pass
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--model', type=str, required=True)
    ap.add_argument('--encoder', type=str, default=None,
                    help="for --model smp: encoder name (e.g. resnet18, "
                         "resnet101)")
    ap.add_argument('--decoder', type=str, default=None,
                    help='for --model smp: one of the 9 smp decoders')
    ap.add_argument('--num_class', type=int, required=True)
    ap.add_argument('--use_aux', action='store_true')
    ap.add_argument('--use_detail_head', action='store_true')
    ap.add_argument('--pth', type=str, required=True,
                    help='reference .pth checkpoint (incl. smp-family '
                         'checkpoints such as the published KD teacher)')
    ap.add_argument('--out', type=str, required=True,
                    help='output orbax checkpoint directory')
    ap.add_argument('--imgh', type=int, default=64,
                    help='init trace height (any valid size works)')
    ap.add_argument('--imgw', type=int, default=64)
    args = ap.parse_args()
    if args.model == 'smp' and not (args.encoder and args.decoder):
        ap.error('--model smp requires --encoder and --decoder (the '
                 'reference stores neither in the .pth)')

    import jax.numpy as jnp
    from rtseg_tpu.config import SegConfig
    from rtseg_tpu.models import get_model
    from rtseg_tpu.train.checkpoint import save_weights_ckpt
    from rtseg_tpu.utils.transplant import load_reference_pth

    cfg = SegConfig(dataset='synthetic', model=args.model,
                    encoder=args.encoder, decoder=args.decoder,
                    num_class=args.num_class, use_aux=args.use_aux,
                    use_detail_head=args.use_detail_head,
                    save_dir='/tmp/rtseg_import')
    cfg.resolve(num_devices=1)
    model = get_model(cfg)
    # smp reorder fixups are keyed per decoder (smp_unet, smp_pan, ...)
    reorder_key = (f'smp_{args.decoder}' if args.model == 'smp'
                   else args.model)
    # PAN's pyramid ladder needs a trace size whose deepest level survives
    # three 2x2 max-pools: os16 encoders need >=128, mit (PAN at os32,
    # reference models/__init__.py:71-75) needs >=256
    min_side = 0
    if args.model == 'smp':
        min_side = 256 if (args.encoder or '').startswith('mit_') else 128
    imgh = max(args.imgh, min_side)
    imgw = max(args.imgw, min_side)
    variables = load_reference_pth(
        args.pth, reorder_key, model,
        jnp.zeros((1, imgh, imgw, 3), jnp.float32))

    out = path.abspath(args.out)
    save_weights_ckpt(out, variables['params'],
                      variables.get('batch_stats', {}),
                      cur_epoch=0, best_score=0.0,
                      imported_from=path.abspath(args.pth))
    n = sum(int(p.size) for p in jax.tree.leaves(variables['params']))
    print(f'Imported {args.pth} -> {out} ({n / 1e6:.2f}M params). '
          f'Use --load_ckpt_path {args.out} for predict/val/fine-tune.')
    return 0


if __name__ == '__main__':
    sys.exit(main())
