"""On-chip knowledge-distillation convergence demo — the reference's full
KD workflow (models/__init__.py:102-122 teacher, core/loss.py:80-87 KL
loss, seg_trainer.py:95-105 in-step teacher forward) exercised end to end
on real hardware with an in-framework-trained teacher:

  1. train an smp DeepLabV3+/ResNet-18 teacher on the learnable synthetic
     dataset and keep its best (EMA) checkpoint;
  2. train a PP-LiteSeg student WITH the frozen teacher in the jit'd step
     (kd_training, KL temperature 4);
  3. train the identical student WITHOUT KD as the control.

Prints one JSON line per phase and a final summary. ~10 min on a v5e chip
(three compiles dominate). Results recorded in CONVERGENCE.md.

    python tools/kd_convergence_demo.py [--steps 400]
"""

import argparse
import json
import sys
from os import path

sys.path.append(path.dirname(path.dirname(path.abspath(__file__))))


def make_config(tag, **kw):
    import jax

    from rtseg_tpu.config import SegConfig
    # keep >=4 steps/epoch whatever the device count (train_bs is
    # per-device; the CPU-mesh smoke runs this on 8 virtual devices)
    bs = kw.get('train_bs', 16)
    base = dict(
        dataset='synthetic', num_class=6,
        synthetic_len=4 * bs * jax.device_count(),
        crop_h=256, crop_w=512, train_bs=bs,
        loss_type='ce', base_lr=0.02, use_ema=True,
        val_interval=10, log_interval=0, use_tb=False,
        random_seed=1,
        save_dir=f'/tmp/rtseg_kd_demo/{tag}',
    )
    base.update(kw)
    return SegConfig(**base)


def train(tag, steps, **kw):
    import shutil

    from rtseg_tpu.train import SegTrainer
    import jax
    cfg = make_config(tag, **kw)
    shutil.rmtree(cfg.save_dir, ignore_errors=True)   # no stale auto-resume
    # synthetic_len / global batch steps per epoch
    iters_per_epoch = max(
        cfg.synthetic_len // (cfg.train_bs * jax.device_count()), 1)
    cfg.total_epoch = max(steps // iters_per_epoch, 1)
    cfg.val_interval = min(cfg.val_interval, cfg.total_epoch)
    cfg.resolve(num_devices=1)
    tr = SegTrainer(cfg)
    tr.run()
    best = float(tr.best_score)
    # a short/degenerate run can end with best==0.0 and no best.ckpt written
    # (the trainer only saves on improvement); the next phase still needs a
    # loadable teacher, so persist the final EMA weights as the best
    best_path = path.join(cfg.save_dir, 'best.ckpt')
    if not path.exists(best_path):
        from rtseg_tpu.train.checkpoint import save_best_ckpt
        save_best_ckpt(best_path, tr.state, cfg.total_epoch, best)
    print(json.dumps({'phase': tag, 'best_miou': round(best, 4),
                      'steps': steps}), flush=True)
    return best, cfg


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--steps', type=int, default=400)
    ap.add_argument('--crop_h', type=int, default=256)
    ap.add_argument('--crop_w', type=int, default=512)
    ap.add_argument('--train_bs', type=int, default=16,
                    help='per-device batch (shrink for the CPU-mesh smoke)')
    args = ap.parse_args()
    size = dict(crop_h=args.crop_h, crop_w=args.crop_w,
                train_bs=args.train_bs)

    teacher_best, teacher_cfg = train(
        'teacher_dlv3p_r18', args.steps,
        model='smp', encoder='resnet18', decoder='deeplabv3p',
        encoder_weights=None, **size)
    teacher_ckpt = path.join(teacher_cfg.save_dir, 'best.ckpt')

    student_kd, _ = train(
        'student_ppliteseg_kd', args.steps,
        model='ppliteseg',
        kd_training=True, teacher_ckpt=teacher_ckpt,
        teacher_model='smp', teacher_encoder='resnet18',
        teacher_decoder='deeplabv3p',
        kd_loss_type='kl_div', kd_temperature=4.0, kd_loss_coefficient=1.0,
        **size)

    student_plain, _ = train('student_ppliteseg_plain', args.steps,
                             model='ppliteseg', **size)

    print(json.dumps({
        'teacher_best_miou': round(teacher_best, 4),
        'student_kd_best_miou': round(student_kd, 4),
        'student_plain_best_miou': round(student_plain, 4),
        'kd_delta': round(student_kd - student_plain, 4),
    }), flush=True)
    return 0


if __name__ == '__main__':
    sys.exit(main())
