"""Capture + aggregate a train-step profiler trace by model module.

The round-3 method that found bisenetv2's DetailBranch at 41% of step time
(BENCHMARKS.md "Flagship train-step profile") as a repeatable tool: jit the
full train step, trace N fenced iterations with jax.profiler, then parse the
trace-viewer JSON and aggregate device time by the model-module prefix XLA
records in each op's metadata (jax source-info -> HLO op_name).

    python tools/profile_step.py --model ddrnet --batch 96
    python tools/profile_step.py --model stdc --batch 128 --hires-remat
    python tools/profile_step.py --inspect   # dump raw event fields

Writes the trace under --trace-dir (default /tmp, NOT the repo: binary
traces stay out of git per the round-3 advisor note) and prints a
module-share table. The traced region is armed with the recompile guard
(rtseg_tpu/analysis/recompile.py): a profile whose iterations secretly
retraced raises instead of attributing compile time to model modules.
"""

import argparse
import collections
import glob
import gzip
import json
import os
import re
import sys
from os import path

sys.path.append(path.dirname(path.dirname(path.abspath(__file__))))

import numpy as np


def capture(model_name, batch, h, w, trace_dir, iters, hires_remat=False,
            detail_remat=False, pack_fullres=False, eval_mode=False):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from rtseg_tpu.config import SegConfig
    from rtseg_tpu.models import get_model
    from rtseg_tpu.models.registry import AUX_MODELS, DETAIL_HEAD_MODELS
    from rtseg_tpu.parallel.mesh import DATA_AXIS
    from rtseg_tpu.train.optim import get_optimizer
    from rtseg_tpu.train.state import create_train_state
    from rtseg_tpu.train.step import build_eval_step, build_train_step

    cfg = SegConfig(dataset='synthetic', model=model_name, num_class=19,
                    compute_dtype='bfloat16', train_bs=batch,
                    use_aux=model_name in AUX_MODELS and not eval_mode,
                    use_detail_head=(model_name in DETAIL_HEAD_MODELS
                                     and not eval_mode),
                    use_ema=True, loss_type='ohem',
                    detail_remat=detail_remat, hires_remat=hires_remat,
                    pack_fullres=pack_fullres,
                    save_dir='/tmp/rtseg_profile')
    cfg.resolve(num_devices=1)
    cfg.resolve_schedule(train_num=batch * 1000)
    model = get_model(cfg)
    opt = get_optimizer(cfg)
    mesh = Mesh(np.array(jax.devices()[:1]), (DATA_AXIS,))
    state = create_train_state(model, opt, jax.random.PRNGKey(0),
                               jnp.zeros((1, h, w, 3), jnp.float32))
    rng = np.random.RandomState(0)
    images = jax.device_put(rng.rand(batch, h, w, 3).astype(np.float32))
    masks = jax.device_put(
        rng.randint(0, 19, (batch, h, w)).astype(np.int32))
    from rtseg_tpu.analysis.recompile import RecompileGuard

    # arm the recompile guard around the traced region: a profile whose
    # iterations secretly retraced would attribute XLA compile time to
    # model modules (same invariant as tools/benchmark_all.py timing)
    if eval_mode:
        step = build_eval_step(cfg, model, mesh)
        step.pin()
        guard = RecompileGuard(f'{model_name} eval profile', warmup=1)
        compiled = step.jitted.lower(
            jax.device_get(state), images, masks).compile()
        cm = compiled(state, images, masks)
        jax.block_until_ready(cm)
        guard.after_call(step.jitted)              # baseline post-warmup
        with jax.profiler.trace(trace_dir):
            for _ in range(iters):
                cm = compiled(state, images, masks)
            jax.block_until_ready(cm)
        guard.after_call(step.jitted)              # raise if trace retraced
        return float(np.asarray(cm).sum())
    step = build_train_step(cfg, model, opt, mesh)
    step.pin()
    guard = RecompileGuard(f'{model_name} train profile', warmup=1)
    compiled = step.jitted.lower(
        jax.device_get(state), images, masks).compile()
    state, _ = compiled(state, images, masks)      # warmup / compile check
    jax.block_until_ready(state)
    guard.after_call(step.jitted)                  # baseline post-warmup
    with jax.profiler.trace(trace_dir):
        for _ in range(iters):
            state, metrics = compiled(state, images, masks)
        jax.block_until_ready(state)
    guard.after_call(step.jitted)                  # raise if trace retraced
    return float(np.asarray(metrics['loss']))


def load_events(trace_dir):
    """All complete ('X') events from the newest trace.json.gz under
    trace_dir, with the process-name map so device tracks are findable."""
    files = sorted(glob.glob(path.join(
        trace_dir, '**', '*.trace.json.gz'), recursive=True),
        key=path.getmtime)
    if not files:
        raise FileNotFoundError(f'no *.trace.json.gz under {trace_dir}')
    with gzip.open(files[-1], 'rt') as f:
        data = json.load(f)
    events = data['traceEvents'] if isinstance(data, dict) else data
    pid_names = {e.get('pid'): e.get('args', {}).get('name', '')
                 for e in events
                 if e.get('ph') == 'M' and e.get('name') == 'process_name'}
    xevents = [e for e in events if e.get('ph') == 'X']
    return xevents, pid_names


# jax records the originating module path in the HLO metadata op_name, which
# the trace viewer surfaces per event (args key varies across versions)
_ARGS_KEYS = ('long_name', 'tf_op', 'hlo_op', 'name')
_MODULE_RE = re.compile(r'([A-Za-z0-9_]+_\d+|[a-z_]+[0-9]?)/')


def module_of(event, depth):
    args = event.get('args', {}) or {}
    meta = ''
    for k in _ARGS_KEYS:
        v = args.get(k, '')
        if isinstance(v, str) and '/' in v:
            meta = v
            break
    if not meta:
        return None
    parts = [p for p in meta.split('/') if p and '=' not in p]
    # drop transpose/jit wrappers so fwd and bwd of one module aggregate
    parts = [p for p in parts if not p.startswith(('jit(', 'transpose('))]
    if not parts:
        return None
    return '/'.join(parts[:depth])


def aggregate(trace_dir, depth):
    events, pid_names = load_events(trace_dir)
    device_pids = {pid for pid, name in pid_names.items()
                   if 'TPU' in name or 'GPU' in name or '/device' in name}
    if not device_pids:
        print('# WARNING: no device (TPU/GPU) process track found — '
              'aggregating HOST events; module shares will be '
              'meaningless for device-time analysis', flush=True)
    dev_events = [e for e in events
                  if (not device_pids or e.get('pid') in device_pids)
                  and float(e.get('dur', 0)) > 0]
    # the device track carries several thread lines: whole-step container
    # events (one per iteration) AND the per-HLO-op line; summing all of
    # them double-counts every cycle. The op-level line is the tid with
    # the most events — aggregate only that one.
    per_line = collections.Counter(
        (e.get('pid'), e.get('tid')) for e in dev_events)
    if per_line:
        op_line = per_line.most_common(1)[0][0]
        dev_events = [e for e in dev_events
                      if (e.get('pid'), e.get('tid')) == op_line]
    rows = collections.Counter()
    total = 0.0
    for e in dev_events:
        dur = float(e.get('dur', 0.0))
        mod = module_of(e, depth)
        total += dur
        rows[mod if mod else '(unattributed)'] += dur
    return rows, total


def inspect(trace_dir, n=15):
    events, pid_names = load_events(trace_dir)
    print('processes:', pid_names)
    shown = 0
    for e in sorted(events, key=lambda e: -float(e.get('dur', 0))):
        print(json.dumps(e)[:400])
        shown += 1
        if shown >= n:
            break


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--model', default='ddrnet')
    ap.add_argument('--batch', type=int, default=96)
    ap.add_argument('--imgh', type=int, default=512)
    ap.add_argument('--imgw', type=int, default=1024)
    ap.add_argument('--iters', type=int, default=6)
    ap.add_argument('--depth', type=int, default=1,
                    help='module-path depth to aggregate at')
    ap.add_argument('--trace-dir', default=None)
    ap.add_argument('--hires-remat', action='store_true')
    ap.add_argument('--detail-remat', action='store_true')
    ap.add_argument('--pack-fullres', action='store_true')
    ap.add_argument('--eval', action='store_true',
                    help='profile the eval step (EMA forward + CM) instead '
                         'of the train step')
    ap.add_argument('--no-capture', action='store_true',
                    help='aggregate an existing trace only')
    ap.add_argument('--inspect', action='store_true',
                    help='dump the longest raw events and exit')
    ap.add_argument('--obs-dir', default=None,
                    help='segscope: write a profile event (model, '
                         'ms/iter, trace dir, module shares) as JSONL '
                         'under this dir, readable by tools/segscope.py')
    args = ap.parse_args()
    trace_dir = args.trace_dir or f'/tmp/rtseg_profile/{args.model}'

    sink = None
    if args.obs_dir:
        from rtseg_tpu import obs
        sink = obs.init_run(args.obs_dir,
                            meta={'tool': 'profile_step',
                                  'model': args.model,
                                  'batch': args.batch,
                                  'imgh': args.imgh, 'imgw': args.imgw})
        obs.set_sink(sink)

    if not args.no_capture and not args.inspect:
        os.makedirs(trace_dir, exist_ok=True)
        loss = capture(args.model, args.batch, args.imgh, args.imgw,
                       trace_dir, args.iters, hires_remat=args.hires_remat,
                       detail_remat=args.detail_remat,
                       pack_fullres=args.pack_fullres, eval_mode=args.eval)
        print(f'# traced {args.iters} iters, fence={loss:.4f}')
    if args.inspect:
        inspect(trace_dir)
        return 0
    rows, total = aggregate(trace_dir, args.depth)
    print(f'\n| module (depth {args.depth}) | device ms/iter | share |')
    print('|---|---|---|')
    for mod, dur in rows.most_common(20):
        print(f'| {mod} | {dur / 1000 / args.iters:.2f} | '
              f'{100 * dur / total:.1f}% |')
    print(f'| TOTAL | {total / 1000 / args.iters:.2f} | 100% |')
    if sink is not None:
        sink.emit({'event': 'profile', 'model': args.model,
                   'mode': 'eval' if args.eval else 'train',
                   'iters': args.iters, 'trace_dir': trace_dir,
                   'ms_per_iter': round(total / 1000 / args.iters, 3),
                   'module_shares': {
                       (mod or '(unattributed)'): round(dur / total, 4)
                       for mod, dur in rows.most_common(20)}})
    return 0


if __name__ == '__main__':
    sys.exit(main())
