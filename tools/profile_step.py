"""Capture + aggregate a train-step profiler trace by model module.

The round-3 method that found bisenetv2's DetailBranch at 41% of step time
(BENCHMARKS.md "Flagship train-step profile") as a repeatable tool: jit the
full train step, trace N fenced iterations with jax.profiler, then parse
the trace with the shared segprof parser (rtseg_tpu/obs/profile.py — the
same DeviceProfile the trainer's sampled profiling and the serve
front-end's `/debug/profile` emit) and print the module-share table.

    python tools/profile_step.py --model ddrnet --batch 96
    python tools/profile_step.py --model stdc --batch 128 --hires-remat
    python tools/profile_step.py --inspect   # dump raw event fields

Writes the trace under --trace-dir (default /tmp, NOT the repo: binary
traces stay out of git per the round-3 advisor note) and prints a
module-share table (falling back to op categories on traces without
module metadata, e.g. the CPU backend). The traced region is armed with
the recompile guard (rtseg_tpu/analysis/recompile.py): a profile whose
iterations secretly retraced raises instead of attributing compile time
to model modules.
"""

import argparse
import json
import os
import sys
from os import path

sys.path.append(path.dirname(path.dirname(path.abspath(__file__))))

import numpy as np

from rtseg_tpu.obs.profile import load_trace_events, parse_trace


def capture(model_name, batch, h, w, trace_dir, iters, hires_remat=False,
            detail_remat=False, pack_fullres=False, eval_mode=False):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from rtseg_tpu.config import SegConfig
    from rtseg_tpu.models import get_model
    from rtseg_tpu.models.registry import AUX_MODELS, DETAIL_HEAD_MODELS
    from rtseg_tpu.parallel.mesh import DATA_AXIS
    from rtseg_tpu.train.optim import get_optimizer
    from rtseg_tpu.train.state import create_train_state
    from rtseg_tpu.train.step import build_eval_step, build_train_step

    cfg = SegConfig(dataset='synthetic', model=model_name, num_class=19,
                    compute_dtype='bfloat16', train_bs=batch,
                    use_aux=model_name in AUX_MODELS and not eval_mode,
                    use_detail_head=(model_name in DETAIL_HEAD_MODELS
                                     and not eval_mode),
                    use_ema=True, loss_type='ohem',
                    detail_remat=detail_remat, hires_remat=hires_remat,
                    pack_fullres=pack_fullres,
                    save_dir='/tmp/rtseg_profile')
    cfg.resolve(num_devices=1)
    cfg.resolve_schedule(train_num=batch * 1000)
    model = get_model(cfg)
    opt = get_optimizer(cfg)
    mesh = Mesh(np.array(jax.devices()[:1]), (DATA_AXIS,))
    state = create_train_state(model, opt, jax.random.PRNGKey(0),
                               jnp.zeros((1, h, w, 3), jnp.float32))
    rng = np.random.RandomState(0)
    images = jax.device_put(rng.rand(batch, h, w, 3).astype(np.float32))
    masks = jax.device_put(
        rng.randint(0, 19, (batch, h, w)).astype(np.int32))
    from rtseg_tpu.analysis.recompile import RecompileGuard

    # arm the recompile guard around the traced region: a profile whose
    # iterations secretly retraced would attribute XLA compile time to
    # model modules (same invariant as tools/benchmark_all.py timing)
    if eval_mode:
        step = build_eval_step(cfg, model, mesh)
        step.pin()
        guard = RecompileGuard(f'{model_name} eval profile', warmup=1)
        compiled = step.jitted.lower(
            jax.device_get(state), images, masks).compile()
        cm = compiled(state, images, masks)
        jax.block_until_ready(cm)
        guard.after_call(step.jitted)              # baseline post-warmup
        with jax.profiler.trace(trace_dir):
            for _ in range(iters):
                cm = compiled(state, images, masks)
            jax.block_until_ready(cm)
        guard.after_call(step.jitted)              # raise if trace retraced
        return float(np.asarray(cm).sum())
    step = build_train_step(cfg, model, opt, mesh)
    step.pin()
    guard = RecompileGuard(f'{model_name} train profile', warmup=1)
    compiled = step.jitted.lower(
        jax.device_get(state), images, masks).compile()
    state, _ = compiled(state, images, masks)      # warmup / compile check
    jax.block_until_ready(state)
    guard.after_call(step.jitted)                  # baseline post-warmup
    with jax.profiler.trace(trace_dir):
        for _ in range(iters):
            state, metrics = compiled(state, images, masks)
        jax.block_until_ready(state)
    guard.after_call(step.jitted)                  # raise if trace retraced
    return float(np.asarray(metrics['loss']))


def inspect(trace_dir, n=15):
    events, pid_names = load_trace_events(trace_dir)
    print('processes:', pid_names)
    shown = 0
    for e in sorted(events, key=lambda e: -float(e.get('dur', 0))):
        print(json.dumps(e)[:400])
        shown += 1
        if shown >= n:
            break


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--model', default='ddrnet')
    ap.add_argument('--batch', type=int, default=96)
    ap.add_argument('--imgh', type=int, default=512)
    ap.add_argument('--imgw', type=int, default=1024)
    ap.add_argument('--iters', type=int, default=6)
    ap.add_argument('--depth', type=int, default=1,
                    help='module-path depth to aggregate at')
    ap.add_argument('--trace-dir', default=None)
    ap.add_argument('--hires-remat', action='store_true')
    ap.add_argument('--detail-remat', action='store_true')
    ap.add_argument('--pack-fullres', action='store_true')
    ap.add_argument('--eval', action='store_true',
                    help='profile the eval step (EMA forward + CM) instead '
                         'of the train step')
    ap.add_argument('--no-capture', action='store_true',
                    help='aggregate an existing trace only')
    ap.add_argument('--inspect', action='store_true',
                    help='dump the longest raw events and exit')
    ap.add_argument('--obs-dir', default=None,
                    help='segscope: write a profile event (model, '
                         'ms/iter, trace dir, module shares) as JSONL '
                         'under this dir, readable by tools/segscope.py')
    args = ap.parse_args()
    trace_dir = args.trace_dir or f'/tmp/rtseg_profile/{args.model}'

    sink = None
    if args.obs_dir:
        from rtseg_tpu import obs
        sink = obs.init_run(args.obs_dir,
                            meta={'tool': 'profile_step',
                                  'model': args.model,
                                  'batch': args.batch,
                                  'imgh': args.imgh, 'imgw': args.imgw})
        obs.set_sink(sink)

    if not args.no_capture and not args.inspect:
        os.makedirs(trace_dir, exist_ok=True)
        loss = capture(args.model, args.batch, args.imgh, args.imgw,
                       trace_dir, args.iters, hires_remat=args.hires_remat,
                       detail_remat=args.detail_remat,
                       pack_fullres=args.pack_fullres, eval_mode=args.eval)
        print(f'# traced {args.iters} iters, fence={loss:.4f}')
    if args.inspect:
        inspect(trace_dir)
        return 0
    prof = parse_trace(trace_dir, depth=args.depth)
    total = prof.busy_us
    if prof.modules:
        rows, what = dict(prof.modules), f'module (depth {args.depth})'
        # device ops with no source-module path (runtime internals)
        # get an explicit row — the table must sum to its own TOTAL
        residue = total - sum(rows.values())
        if total > 0 and residue / total > 1e-4:
            rows['(unattributed)'] = residue
    else:
        # traces without module metadata (CPU backend) still attribute
        # by op category — never an empty table
        rows, what = prof.categories, 'op category'
        if not prof.device_track:
            print('# WARNING: no device (TPU/GPU) process track found — '
                  'aggregated the XLA op events of the host backend; '
                  'module paths unavailable, showing op categories',
                  flush=True)
    print(f'\n| {what} | device ms/iter | share |')
    print('|---|---|---|')
    for mod, dur in sorted(rows.items(), key=lambda kv: -kv[1])[:20]:
        print(f'| {mod} | {dur / 1000 / args.iters:.2f} | '
              f'{100 * dur / total:.1f}% |')
    print(f'| TOTAL | {total / 1000 / args.iters:.2f} | 100% | '
          f'(busy {100 * prof.busy_frac:.1f}% of the capture window, '
          f'{100 * prof.attributed_frac:.1f}% attributed)')
    if sink is not None:
        sink.emit(prof.to_event(
            model=args.model, mode='eval' if args.eval else 'train',
            iters=args.iters, trace_dir=trace_dir,
            ms_per_iter=round(total / 1000 / args.iters, 3),
            module_shares={mod: round(dur / total, 4)
                           for mod, dur in sorted(rows.items(),
                                                  key=lambda kv: -kv[1])
                           [:20] if total}))
    return 0


if __name__ == '__main__':
    sys.exit(main())
