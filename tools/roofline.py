"""Analytical roofline for the model zoo — the chip-free half of the MFU
story (VERDICT round-2 weak #1: "prove the ceiling per shape class").

For each model the forward program is AOT-lowered from abstract shapes (no
allocation, works with no accelerator) and XLA's cost analysis provides
FLOPs and bytes accessed. Arithmetic intensity I = flops/bytes against the
device ridge point R = peak_flops/HBM_bw decides the bound:

    attainable FLOP/s = min(peak, I * bw)   ->   ceiling MFU = attainable/peak

Round-3 addition: a bytes-weighted vector-lane occupancy estimate per
model (see lane_occupancy) scales the bandwidth term — thin-channel convs
get batch-in-lanes layouts on TPU, so at small batch most of the 128
lanes carry padding and the plain roofline over-predicts the attainable
bandwidth. The lane-adjusted ceiling explains the measured bs32 vs bs128
gap (BENCHMARKS.md round-3 section).

Caveat stated up front: 'bytes accessed' is measured on the *compiling*
backend's post-fusion HLO. The default --backend cpu compiles everywhere
but fuses differently from TPU (typically over-counting bytes, so the
ceiling is pessimistic); pass --backend tpu on a live chip for
TPU-post-fusion counts. Peak/bandwidth default to TPU v5e; override with
--peak-flops / --bw for other generations (see
tools/benchmark_all.py PEAK_BF16_BY_KIND for peaks).

    python tools/roofline.py --models fastscnn,bisenetv2

`--json` (one object per model per line) is the format
`tools/segscope.py report --roofline` consumes: the report's
measured-MFU line divides measured device busy time (segprof profile
events, rtseg_tpu/obs/profile.py) into the lane-adjusted ceiling here.
"""

import argparse
import json
import sys
from os import path

sys.path.append(path.dirname(path.dirname(path.abspath(__file__))))

from benchmark_all import compiled_costs  # noqa: E402

# defaults: TPU v5e, 197 TFLOP/s bf16, 819 GB/s HBM
PEAK_V5E = 197e12
BW_V5E = 819e9
# v5e int8 peak: 394 TOP/s (2x bf16) — the segquant ceiling row. The
# int8 ceiling below reuses the bf16-program byte counts (conservative:
# int8 weights move ~4x fewer bytes, so a bandwidth-bound model's real
# int8 ceiling is HIGHER than printed), because cost analysis of the
# quantized program would need the dequant-in-graph trace per model and
# the pessimistic bound is the honest default
PEAK_INT8_V5E = 394e12

DEFAULT_MODELS = ('fastscnn,bisenetv2,ddrnet,stdc,ppliteseg,enet,esnet,'
                  'erfnet,mininetv2,fddwnet')


LANES = 128  # v5e vector lanes; one tile minor dim


def _model_forward(name, batch, h, w):
    import jax
    import jax.numpy as jnp
    from rtseg_tpu.config import SegConfig
    from rtseg_tpu.models import get_model

    cfg = SegConfig(dataset='synthetic', model=name, num_class=19,
                    save_dir='/tmp/rtseg_roofline')
    cfg.resolve(num_devices=1)
    m = get_model(cfg)
    shapes = jax.eval_shape(
        lambda: m.init(jax.random.PRNGKey(0),
                       jnp.zeros((1, h, w, 3), jnp.float32), False))
    x = jax.ShapeDtypeStruct((batch, h, w, 3), jnp.bfloat16)
    fn = lambda v, x: m.apply(v, x, False).astype(jnp.float32).sum()  # noqa: E731
    return fn, shapes, x


def _costs(fn, shapes, x):
    import jax
    return compiled_costs(jax.jit(fn).lower(shapes, x).compile())


def lane_occupancy(name, batch, h, w):
    """Bytes-weighted vector-lane occupancy estimate over the model's convs.

    The round-3 esnet profiler trace (BENCHMARKS.md) showed XLA compiles
    convs whose channel count can't fill the 128 lanes with batch-in-lanes
    emitters, so the lanes carry whichever of {channels, batch} is larger:
    per conv output, occ = min(1, max(C_out, B) / 128), weighted by output
    bytes (the tensors whose traffic the lanes gate). This is the factor
    the plain byte-count roofline misses — it predicted esnet bs32 at its
    ceiling when the chip had 4x more lanes to give (233 -> 1237 imgs/sec
    measured at bs128).

    Walks the *traced* jaxpr (backend-independent, no compile needed).
    """
    fn, shapes, x = _model_forward(name, batch, h, w)
    return _lane_occupancy(fn, shapes, x)


def _lane_occupancy(fn, shapes, x):
    import jax

    jaxpr = jax.make_jaxpr(fn)(shapes, x)

    weighted = total = 0.0
    def visit(jp):
        nonlocal weighted, total
        for eqn in jp.eqns:
            for sub in eqn.params.values():
                if hasattr(sub, 'jaxpr'):          # nested (pjit, remat...)
                    visit(sub.jaxpr)
                elif isinstance(sub, (tuple, list)):
                    # params holding SEQUENCES of ClosedJaxprs (cond
                    # branches, scan bodies) would otherwise be silently
                    # skipped and their convs dropped from the estimate
                    for el in sub:
                        if hasattr(el, 'jaxpr'):
                            visit(el.jaxpr)
            if eqn.primitive.name != 'conv_general_dilated':
                continue
            aval = eqn.outvars[0].aval
            if len(aval.shape) != 4:
                continue
            b, c = aval.shape[0], aval.shape[-1]   # NHWC throughout the zoo
            by = aval.size * aval.dtype.itemsize
            weighted += by * min(1.0, max(c, b) / LANES)
            total += by
    visit(jaxpr.jaxpr)
    return weighted / total if total else 1.0


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--models', type=str, default=DEFAULT_MODELS)
    ap.add_argument('--batch', type=int, default=32)
    ap.add_argument('--imgh', type=int, default=512)
    ap.add_argument('--imgw', type=int, default=1024)
    ap.add_argument('--backend', type=str, default='cpu',
                    help="compile backend for the byte counts ('tpu' on a "
                         'live chip for TPU-post-fusion numbers)')
    ap.add_argument('--peak-flops', type=float, default=PEAK_V5E,
                    help='device peak FLOP/s for the MFU denominator')
    ap.add_argument('--peak-flops-int8', type=float,
                    default=PEAK_INT8_V5E,
                    help='device peak int8 OP/s (segquant ceiling row; '
                         'v5e: 2x the bf16 peak)')
    ap.add_argument('--bw', type=float, default=BW_V5E,
                    help='device HBM bandwidth, bytes/s')
    ap.add_argument('--json', action='store_true',
                    help='emit one JSON line per model instead of the '
                         'markdown table')
    args = ap.parse_args()

    import jax
    try:
        # the axon sitecustomize overrides JAX_PLATFORMS; honor --backend
        # in-process
        jax.config.update('jax_platforms', args.backend)
    except Exception:
        pass

    peak, bw = args.peak_flops, args.bw
    peak_i8 = args.peak_flops_int8
    ridge = peak / bw
    if not args.json:
        print(f'| model | GFLOPs/img | GB/img | intensity (FLOP/B) | '
              f'roofline-bound | est. ceiling MFU | lane occ @bs{args.batch} '
              f'| lane-adj ceiling | int8 ceiling |')
        print('|---|---|---|---|---|---|---|---|---|')
    for name in [s.strip() for s in args.models.split(',') if s.strip()]:
        try:
            fn, shapes, x = _model_forward(name, args.batch, args.imgh,
                                           args.imgw)
            flops, bytes_ = _costs(fn, shapes, x)
            occ = _lane_occupancy(fn, shapes, x)
        except Exception as e:
            msg = f'{type(e).__name__}: {e}'.replace('|', '/')
            msg = ' '.join(msg.split())[:120]
            if args.json:
                print(json.dumps({'model': name, 'error': msg}), flush=True)
            else:
                print(f'| {name} | FAILED: {msg} | — | — | — | — | — | — '
                      f'| — |', flush=True)
            continue
        fpi, bpi = flops / args.batch, bytes_ / args.batch
        inten = fpi / bpi if bpi else float('inf')
        attain = min(peak, inten * bw)
        # lanes carrying padding derate *effective* bandwidth, so the
        # adjusted ceiling scales the bandwidth term by occupancy; this can
        # pull a nominally compute-bound shape below peak too (padding
        # traffic is real even when intensity clears the ridge)
        attain_occ = min(peak, inten * bw * occ)
        # int8 ceiling: the same intensity/bandwidth against the int8
        # peak (see PEAK_INT8_V5E note — byte counts stay the bf16
        # program's, so this row is a conservative lower bound)
        attain_i8 = min(peak_i8, inten * bw)
        attain_i8_occ = min(peak_i8, inten * bw * occ)
        if args.json:
            print(json.dumps({'model': name,
                              'gflops_per_img': round(fpi / 1e9, 3),
                              'gb_per_img': round(bpi / 1e9, 4),
                              'intensity': round(inten, 2),
                              'ceiling_mfu': round(attain / peak, 4),
                              'lane_occupancy': round(occ, 4),
                              'lane_adj_ceiling_mfu':
                                  round(attain_occ / peak, 4),
                              'int8_ceiling_mfu':
                                  round(attain_i8 / peak_i8, 4),
                              'lane_adj_int8_ceiling_mfu':
                                  round(attain_i8_occ / peak_i8, 4)}),
                  flush=True)
        else:
            bound = 'compute' if inten >= ridge else 'bandwidth'
            print(f'| {name} | {fpi / 1e9:.2f} | {bpi / 1e9:.3f} | '
                  f'{inten:.1f} | {bound} | {100 * attain / peak:.1f}% | '
                  f'{occ:.2f} | {100 * attain_occ / peak:.1f}% | '
                  f'{100 * attain_i8_occ / peak_i8:.1f}% |',
                  flush=True)
    if not args.json:
        print(f'\nridge point: {ridge:.0f} FLOP/B '
              f'({peak / 1e12:.0f} TF / {bw / 1e9:.0f} GB/s, '
              f'{args.backend}-post-fusion byte counts)')
    return 0


if __name__ == '__main__':
    sys.exit(main())
