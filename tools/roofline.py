"""Analytical roofline for the model zoo — the chip-free half of the MFU
story (VERDICT round-2 weak #1: "prove the ceiling per shape class").

For each model the forward program is AOT-lowered from abstract shapes (no
allocation, works with no accelerator) and XLA's cost analysis provides
FLOPs and bytes accessed. Arithmetic intensity I = flops/bytes against the
device ridge point R = peak_flops/HBM_bw decides the bound:

    attainable FLOP/s = min(peak, I * bw)   ->   ceiling MFU = attainable/peak

Caveat stated up front: 'bytes accessed' is measured on the *compiling*
backend's post-fusion HLO. The default --backend cpu compiles everywhere
but fuses differently from TPU (typically over-counting bytes, so the
ceiling is pessimistic); pass --backend tpu on a live chip for
TPU-post-fusion counts. Peak/bandwidth default to TPU v5e; override with
--peak-flops / --bw for other generations (see
tools/benchmark_all.py PEAK_BF16_BY_KIND for peaks).

    python tools/roofline.py --models fastscnn,bisenetv2
"""

import argparse
import json
import sys
from os import path

sys.path.append(path.dirname(path.dirname(path.abspath(__file__))))

from benchmark_all import compiled_costs  # noqa: E402

# defaults: TPU v5e, 197 TFLOP/s bf16, 819 GB/s HBM
PEAK_V5E = 197e12
BW_V5E = 819e9

DEFAULT_MODELS = ('fastscnn,bisenetv2,ddrnet,stdc,ppliteseg,enet,esnet,'
                  'erfnet,mininetv2,fddwnet')


def analyze(name, batch, h, w):
    import jax
    import jax.numpy as jnp
    from rtseg_tpu.config import SegConfig
    from rtseg_tpu.models import get_model

    cfg = SegConfig(dataset='synthetic', model=name, num_class=19,
                    save_dir='/tmp/rtseg_roofline')
    cfg.resolve(num_devices=1)
    m = get_model(cfg)
    shapes = jax.eval_shape(
        lambda: m.init(jax.random.PRNGKey(0),
                       jnp.zeros((1, h, w, 3), jnp.float32), False))
    x = jax.ShapeDtypeStruct((batch, h, w, 3), jnp.bfloat16)
    f = jax.jit(lambda v, x: m.apply(v, x, False).astype(jnp.float32).sum())
    return compiled_costs(f.lower(shapes, x).compile())


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--models', type=str, default=DEFAULT_MODELS)
    ap.add_argument('--batch', type=int, default=32)
    ap.add_argument('--imgh', type=int, default=512)
    ap.add_argument('--imgw', type=int, default=1024)
    ap.add_argument('--backend', type=str, default='cpu',
                    help="compile backend for the byte counts ('tpu' on a "
                         'live chip for TPU-post-fusion numbers)')
    ap.add_argument('--peak-flops', type=float, default=PEAK_V5E,
                    help='device peak FLOP/s for the MFU denominator')
    ap.add_argument('--bw', type=float, default=BW_V5E,
                    help='device HBM bandwidth, bytes/s')
    ap.add_argument('--json', action='store_true',
                    help='emit one JSON line per model instead of the '
                         'markdown table')
    args = ap.parse_args()

    import jax
    try:
        # the axon sitecustomize overrides JAX_PLATFORMS; honor --backend
        # in-process
        jax.config.update('jax_platforms', args.backend)
    except Exception:
        pass

    peak, bw = args.peak_flops, args.bw
    ridge = peak / bw
    if not args.json:
        print(f'| model | GFLOPs/img | GB/img | intensity (FLOP/B) | '
              f'roofline-bound | est. ceiling MFU |')
        print('|---|---|---|---|---|---|')
    for name in [s.strip() for s in args.models.split(',') if s.strip()]:
        try:
            flops, bytes_ = analyze(name, args.batch, args.imgh, args.imgw)
        except Exception as e:
            msg = f'{type(e).__name__}: {e}'.replace('|', '/')
            msg = ' '.join(msg.split())[:120]
            if args.json:
                print(json.dumps({'model': name, 'error': msg}), flush=True)
            else:
                print(f'| {name} | FAILED: {msg} | — | — | — | — |',
                      flush=True)
            continue
        fpi, bpi = flops / args.batch, bytes_ / args.batch
        inten = fpi / bpi if bpi else float('inf')
        attain = min(peak, inten * bw)
        if args.json:
            print(json.dumps({'model': name,
                              'gflops_per_img': round(fpi / 1e9, 3),
                              'gb_per_img': round(bpi / 1e9, 4),
                              'intensity': round(inten, 2),
                              'ceiling_mfu': round(attain / peak, 4)}),
                  flush=True)
        else:
            bound = 'compute' if inten >= ridge else 'bandwidth'
            print(f'| {name} | {fpi / 1e9:.2f} | {bpi / 1e9:.3f} | '
                  f'{inten:.1f} | {bound} | {100 * attain / peak:.1f}% |',
                  flush=True)
    if not args.json:
        print(f'\nridge point: {ridge:.0f} FLOP/B '
              f'({peak / 1e12:.0f} TF / {bw / 1e9:.0f} GB/s, '
              f'{args.backend}-post-fusion byte counts)')
    return 0


if __name__ == '__main__':
    sys.exit(main())
