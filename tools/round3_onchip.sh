#!/bin/bash
# Round-3 deferred on-chip measurement queue (run when the axon tunnel is
# back; one TPU workload at a time — concurrent processes wedge the tunnel).
# Each step appends to round3_onchip.log; safe to re-run from any step.
set -x
cd "$(dirname "$0")/.."
LOG=round3_onchip.log
{
date
# 0. tunnel sanity (fast jit)
timeout 300 python -c "import jax; import jax.numpy as jnp; print(jax.devices()); x=jnp.ones((8,8)); print((x@x).sum())" || exit 1

# 1. headline (driver contract)
python bench.py

# 2. forward MFU rows for the headline models (completes the round-2 column)
python tools/benchmark_all.py --models bisenetv2,fastscnn,ddrnet,stdc,ppliteseg,esnet,erfnet,mininetv2,fddwnet

# 3. train-step MFU (never measured; VERDICT round-2 #1)
python tools/benchmark_all.py --train --batch 96 --models bisenetv2,fastscnn,ddrnet,stdc

# 4. s2d stem packing A/B (same models, forward + train)
python tools/benchmark_all.py --s2d --models bisenetv2,fastscnn,ddrnet,stdc
python tools/benchmark_all.py --s2d --train --batch 96 --models bisenetv2,fastscnn,ddrnet,stdc

# 5. segnet bs64: baseline repro (expected OOM) then the S2D mitigation
python tools/benchmark_all.py --models segnet --batch 64
python tools/benchmark_all.py --models segnet --batch 64 --segnet-pack

# 6. esnet profiler trace (decides the intrinsic-ceiling claim)
python - <<'EOF'
import jax, numpy as np, jax.numpy as jnp
from rtseg_tpu.config import SegConfig
from rtseg_tpu.models import get_model
cfg = SegConfig(dataset='synthetic', model='esnet', num_class=19,
                save_dir='/tmp/rtseg_trace')
cfg.resolve(num_devices=1)
m = get_model(cfg)
x = jax.device_put(np.random.rand(32, 512, 1024, 3).astype(np.float32)
                   ).astype(jnp.bfloat16)
v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 512, 1024, 3)), False)
f = jax.jit(lambda v, x: m.apply(v, x, False).astype(jnp.float32).sum())
c = f.lower(v, x).compile()
c(v, x).block_until_ready()
with jax.profiler.trace('/root/repo/traces/esnet'):
    for _ in range(8):
        r = c(v, x)
    r.block_until_ready()
print('trace written to traces/esnet')
EOF
date
} 2>&1 | tee -a "$LOG"
