#!/bin/bash
# Round-3 second on-chip queue — batch/lane follow-ups to the measurements
# in round3_onchip.log (one TPU workload at a time; appends to
# round3b_onchip.log; safe to re-run from any step).
#
# Motivation (BENCHMARKS.md round-3 section): bs128 fills the 128 vector
# lanes for batch-in-lanes conv layouts. The train table was measured at
# bs96 and the full-res eval table at bs8 — both leave lanes empty.
set -x -o pipefail
cd "$(dirname "$0")/.."
LOG=round3b_onchip.log
{
date
# 0. tunnel sanity
timeout 300 python -c "import jax; import jax.numpy as jnp; print(jax.devices()); x=jnp.ones((8,8)); print((x@x).sum())" || exit 1

# 1. train step at lane-filling bs128 (bisenetv2 OOMed at bs128 in round 2;
#    the others were never tried)
python tools/benchmark_all.py --train --batch 128 --models fastscnn,stdc,ddrnet

# 2. full-res eval at lane-filling batch (table stands at bs8)
python tools/benchmark_all.py --eval --batch 32 --imgh 1024 --imgw 2048 --models fastscnn,ppliteseg,stdc,ddrnet
python tools/benchmark_all.py --eval --batch 16 --imgh 1024 --imgw 2048 --models bisenetv2
date
} 2>&1 | tee -a "$LOG"
exit "${PIPESTATUS[0]}"
