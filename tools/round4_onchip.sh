#!/bin/bash
# Round-4 first on-chip queue (one TPU workload at a time; appends to
# round4_onchip.log; safe to re-run from any step).
#
# Covers the VERDICT round-3 items measurable with existing code:
#   - item 8: bs1 latency honesty row (reference protocol is bare forward
#     at 1024x512 bs1, /root/reference/tools/test_speed.py:9-61)
#   - ADVICE item 1: the bs64 full-res eval numbers asserted in
#     BENCHMARKS.md without a committed evidence log
#   - item 6: Pallas CM vs einsum CM on the integrated eval path at the
#     serving shape (2048x1024 bs16)
set -x -o pipefail
cd "$(dirname "$0")/.."
LOG=round4_onchip.log
{
date
# 0. tunnel sanity
timeout 300 python -c "import jax; import jax.numpy as jnp; print(jax.devices()); x=jnp.ones((8,8)); print((x@x).sum())" || exit 1

# 1. bs1 latency (reference protocol shape)
python tools/benchmark_all.py --batch 1 --models fastscnn,bisenetv2,ddrnet,stdc,ppliteseg,enet

# 2. bs64 full-res eval evidence log (numbers previously asserted unlogged)
python tools/benchmark_all.py --eval --batch 64 --imgh 1024 --imgw 2048 --models fastscnn,ddrnet,ppliteseg,stdc

# 3. Pallas CM vs einsum CM, same compiled eval step otherwise
python tools/benchmark_all.py --eval --batch 16 --imgh 1024 --imgw 2048 --models bisenetv2,fastscnn
python tools/benchmark_all.py --eval --batch 16 --imgh 1024 --imgw 2048 --pallas-cm --models bisenetv2,fastscnn
date
} 2>&1 | tee -a "$LOG"
exit "${PIPESTATUS[0]}"
