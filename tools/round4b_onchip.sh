#!/bin/bash
# Round-4 second on-chip queue: remat generalization (VERDICT r3 item 3)
# and the full-res eval attack (item 4). One TPU workload at a time;
# appends to round4b_onchip.log; safe to re-run from any step.
set -x -o pipefail
cd "$(dirname "$0")/.."
LOG=round4b_onchip.log
{
date
# 0. tunnel sanity
timeout 300 python -c "import jax; import jax.numpy as jnp; print(jax.devices()); x=jnp.ones((8,8)); print((x@x).sum())" || exit 1

# 1. pre-remat train profiles: find each model's dominant branch
#    (the 41fc827 method; event-args sanity first via --inspect)
python tools/profile_step.py --model ddrnet --batch 96 --iters 6 --depth 1
python tools/profile_step.py --model ddrnet --no-capture --inspect | head -20
python tools/profile_step.py --model stdc --batch 96 --iters 6 --depth 1
python tools/profile_step.py --model ppliteseg --batch 96 --iters 6 --depth 2

# 2. ppliteseg bs128 baseline (never measured) + hires-remat bs128 sweep
python tools/benchmark_all.py --train --batch 128 --models ppliteseg
python tools/benchmark_all.py --train --batch 128 --hires-remat --models ddrnet,stdc,ppliteseg

# 3. bisenetv2 full-res eval profile (where do the 14.3%-MFU cycles go?)
python tools/profile_step.py --eval --model bisenetv2 --batch 16 --imgh 1024 --imgw 2048 --iters 6 --depth 1

# 4. re-measure the Pallas CM with the final int32-accumulate kernel
#    (batch-1 numbers were the f32-accumulate draft)
python tools/benchmark_all.py --eval --batch 16 --imgh 1024 --imgw 2048 --pallas-cm --models bisenetv2,fastscnn
date
} 2>&1 | tee -a "$LOG"
exit "${PIPESTATUS[0]}"
