#!/bin/bash
# Round-4 third on-chip queue: hires_remat in its real use case — the
# reference's 1024x1024 train crop (README.md:174-175), where activation
# memory doubles vs the 1024x512 bench shape and the lane-filling batch
# may not fit without remat. A/B max-batch and throughput.
set -x -o pipefail
cd "$(dirname "$0")/.."
LOG=round4c_onchip.log
{
date
timeout 300 python -c "import jax; import jax.numpy as jnp; print(jax.devices()); x=jnp.ones((8,8)); print((x@x).sum())" || exit 1

# baseline 1024^2: expect OOM at bs128 somewhere; probe 64 then 128
python tools/benchmark_all.py --train --batch 64 --imgh 1024 --imgw 1024 --models stdc,ddrnet,ppliteseg
python tools/benchmark_all.py --train --batch 128 --imgh 1024 --imgw 1024 --models stdc,ddrnet,ppliteseg
# remat 1024^2 at the same batches
python tools/benchmark_all.py --train --batch 128 --imgh 1024 --imgw 1024 --hires-remat --models stdc,ddrnet,ppliteseg
# bisenetv2 1024^2 for the full flagship picture (detail_remat lever)
python tools/benchmark_all.py --train --batch 64 --imgh 1024 --imgw 1024 --detail-remat --models bisenetv2
python tools/benchmark_all.py --train --batch 128 --imgh 1024 --imgw 1024 --detail-remat --models bisenetv2
date
} 2>&1 | tee -a "$LOG"
exit "${PIPESTATUS[0]}"
