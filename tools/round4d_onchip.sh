#!/bin/bash
# Round-4 fourth on-chip queue: the bisenetv2 pack_fullres eval A/B
# (VERDICT r3 item 4 — attack the 14.3%-MFU full-res serving shape).
set -x -o pipefail
cd "$(dirname "$0")/.."
LOG=round4d_onchip.log
{
date
timeout 300 python -c "import jax; import jax.numpy as jnp; print(jax.devices()); x=jnp.ones((8,8)); print((x@x).sum())" || exit 1

# packed vs standard eval at the serving shape (standard bs16 baseline =
# 161-166 imgs/sec, round4_onchip.log)
python tools/benchmark_all.py --eval --batch 16 --imgh 1024 --imgw 2048 --pack-fullres --models bisenetv2
# packed halves the stem HBM: probe the next batch up
python tools/benchmark_all.py --eval --batch 32 --imgh 1024 --imgw 2048 --pack-fullres --models bisenetv2
python tools/benchmark_all.py --eval --batch 32 --imgh 1024 --imgw 2048 --models bisenetv2
# packed eval profile: where does the time go now?
python tools/profile_step.py --eval --model bisenetv2 --batch 16 --imgh 1024 --imgw 2048 --iters 6 --depth 3 --pack-fullres
date
} 2>&1 | tee -a "$LOG"
exit "${PIPESTATUS[0]}"
