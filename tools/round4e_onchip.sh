#!/bin/bash
# Round-4 fifth on-chip queue: stdc at its own memory-bound shape (bs64
# 1024^2 baseline OOMs — round4c) with hires_remat, + the driver bench
# sanity (verify surface).
set -x -o pipefail
cd "$(dirname "$0")/.."
LOG=round4e_onchip.log
{
date
timeout 300 python -c "import jax; import jax.numpy as jnp; print(jax.devices()); x=jnp.ones((8,8)); print((x@x).sum())" || exit 1
python tools/benchmark_all.py --train --batch 64 --imgh 1024 --imgw 1024 --hires-remat --models stdc
python tools/benchmark_all.py --train --batch 32 --imgh 1024 --imgw 1024 --models stdc
# full-res eval batch scaling now that the Pallas CM freed the one-hot HBM
python tools/benchmark_all.py --eval --batch 64 --imgh 1024 --imgw 2048 --models bisenetv2
# attribution control: einsum CM at bs32 (did the Pallas CM unlock bs32?)
python tools/benchmark_all.py --eval --batch 32 --imgh 1024 --imgw 2048 --no-pallas-cm --models bisenetv2
python bench.py
date
} 2>&1 | tee -a "$LOG"
exit "${PIPESTATUS[0]}"
