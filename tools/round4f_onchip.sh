#!/bin/bash
# Round-4 sixth on-chip queue: full-res EVAL at the bs128 lane knee for
# the flagship set (bs64 table: fastscnn 696@13.4%, ddrnet 468@23.9%,
# ppliteseg 434@21.1%, stdc 380@29.3%, bisenetv2 326@28.7% — the train
# knee says 128 lanes want 128 batch elements for thin-channel convs).
set -x -o pipefail
cd "$(dirname "$0")/.."
LOG=round4f_onchip.log
{
date
timeout 300 python -c "import jax; import jax.numpy as jnp; print(jax.devices()); x=jnp.ones((8,8)); print((x@x).sum())" || exit 1
python tools/benchmark_all.py --eval --batch 128 --imgh 1024 --imgw 2048 --models fastscnn,ppliteseg,stdc,ddrnet,bisenetv2 || echo "## STEP FAILED rc=$? (queue continues)"
python tools/benchmark_all.py --eval --batch 64 --imgh 1024 --imgw 2048 --models bisenetv2,enet || echo "## STEP FAILED rc=$? (queue continues)"
date
} 2>&1 | tee -a "$LOG"
exit "${PIPESTATUS[0]}"
