#!/bin/bash
# Round-4 seventh on-chip queue: zoo-wide full-res (2048x1024) eval at the
# bs128 knee — extends the flagship serving table across the zoo. Models
# that OOM at bs128 fall through (the sweep reports FAILED and continues);
# segnet runs with its S2D packing at its known-good bs64.
set -x -o pipefail
cd "$(dirname "$0")/.."
LOG=round4g_onchip.log
{
date
timeout 300 python -c "import jax; import jax.numpy as jnp; print(jax.devices()); x=jnp.ones((8,8)); print((x@x).sum())" || exit 1
python tools/benchmark_all.py --eval --batch 128 --imgh 1024 --imgw 2048 --models erfnet,bisenetv1,esnet,cgnet,contextnet,dabnet || echo "## STEP FAILED rc=$? (queue continues)"
python tools/benchmark_all.py --eval --batch 128 --imgh 1024 --imgw 2048 --models lednet,linknet,swiftnet,edanet,fssnet,sqnet || echo "## STEP FAILED rc=$? (queue continues)"
python tools/benchmark_all.py --eval --batch 64 --imgh 1024 --imgw 2048 --segnet-pack --models segnet || echo "## STEP FAILED rc=$? (queue continues)"
date
} 2>&1 | tee -a "$LOG"
exit "${PIPESTATUS[0]}"
