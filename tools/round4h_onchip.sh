#!/bin/bash
# Round-4 eighth on-chip queue: bs64 full-res eval for the models that
# OOM at bs128, + segnet-pack at a full-res-feasible batch.
set -x -o pipefail
cd "$(dirname "$0")/.."
LOG=round4h_onchip.log
{
date
timeout 300 python -c "import jax; import jax.numpy as jnp; print(jax.devices()); x=jnp.ones((8,8)); print((x@x).sum())" || exit 1
python tools/benchmark_all.py --eval --batch 64 --imgh 1024 --imgw 2048 --models bisenetv1,cgnet,contextnet,lednet,swiftnet,edanet,sqnet || echo "## STEP FAILED rc=$? (queue continues)"
python tools/benchmark_all.py --eval --batch 16 --imgh 1024 --imgw 2048 --segnet-pack --models segnet || echo "## STEP FAILED rc=$? (queue continues)"
date
} 2>&1 | tee -a "$LOG"
exit "${PIPESTATUS[0]}"
