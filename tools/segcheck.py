#!/usr/bin/env python
"""segcheck — static analysis + trace audit gate for rtseg_tpu.

Usage:
    python tools/segcheck.py                 # all lint rules + zoo audit
    python tools/segcheck.py --lint-only     # AST rules only (no jax)
    python tools/segcheck.py --rules import-hygiene,evidence-citation
    python tools/segcheck.py --audit-only    # eval_shape zoo sweep only

Rules (suppress one finding with `# segcheck: disable=<rule>` on its line):
    import-hygiene        torch/torchvision never import at module scope
    registry-consistency  models/ files <-> MODEL_REGISTRY, classes exist
    trace-purity          no print/np.random/time/datetime in jit'd code
    evidence-citation     measurement claims cite real BENCHMARKS.md
                          headings or committed logs

Audit: jax.eval_shape sweep of every registry model (aux/detail variants
included) asserting the [B, H, W, num_class] eval contract — no weights
materialized, CPU-safe.

Exit codes: 0 clean, 1 findings/audit failures, 2 usage or internal error.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rtseg_tpu.analysis.core import ALL_RULES, repo_root, run_lints  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog='segcheck', description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument('--root', default=None,
                    help='repo root (default: auto-detected)')
    ap.add_argument('--rules', default=None,
                    help=f'comma-separated rule subset of {ALL_RULES}')
    ap.add_argument('--lint-only', action='store_true',
                    help='skip the eval_shape zoo audit (no jax import)')
    ap.add_argument('--audit-only', action='store_true',
                    help='run only the eval_shape zoo audit')
    ap.add_argument('--num-class', type=int, default=19,
                    help='audit num_class (default 19, Cityscapes)')
    ap.add_argument('-q', '--quiet', action='store_true',
                    help='print findings only, no summary')
    args = ap.parse_args(argv)
    if args.lint_only and args.audit_only:
        ap.error('--lint-only and --audit-only are mutually exclusive')

    try:
        root = args.root or repo_root()
    except FileNotFoundError as e:
        print(f'segcheck: {e}', file=sys.stderr)
        return 2

    failures = 0
    if not args.audit_only:
        rules = [r.strip() for r in args.rules.split(',')] \
            if args.rules else None
        try:
            findings = run_lints(root, rules)
        except ValueError as e:
            print(f'segcheck: {e}', file=sys.stderr)
            return 2
        for f in findings:
            print(f)
        failures += len(findings)
        if not args.quiet:
            n = len(findings)
            print(f'segcheck lint: {n} finding(s)'
                  f' across {len(rules or ALL_RULES)} rule(s)')

    if not args.lint_only:
        # deferred import: the lint half must work without jax installed.
        # The audit needs no accelerator (eval_shape is pure tracing), so
        # default to CPU — and pin it through jax.config too, because the
        # axon sitecustomize overrides JAX_PLATFORMS at interpreter start
        # (same counter-override as tests/conftest.py)
        os.environ.setdefault('JAX_PLATFORMS', 'cpu')
        import jax
        try:
            jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])
        except Exception:
            pass
        from rtseg_tpu.analysis.shape_audit import audit_zoo
        report = audit_zoo(num_class=args.num_class)
        bad = [r for r in report if not r.ok]
        for r in bad:
            print(f'audit: {r}')
        failures += len(bad)
        if not args.quiet:
            print(f'segcheck audit: {len(report) - len(bad)}/{len(report)} '
                  f'zoo variants pass the shape/dtype contract')

    return 1 if failures else 0


if __name__ == '__main__':
    sys.exit(main())
