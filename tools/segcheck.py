#!/usr/bin/env python
"""segcheck — static analysis + trace audit gate for rtseg_tpu.

Usage:
    python tools/segcheck.py                 # all lint rules + zoo audit
    python tools/segcheck.py --lint-only     # AST rules only (no jax)
    python tools/segcheck.py --rules import-hygiene,evidence-citation
    python tools/segcheck.py --audit-only    # eval_shape zoo sweep only
    python tools/segcheck.py --deep          # + jaxpr/HLO deep audits
    python tools/segcheck.py --deep --update-budget   # re-pin SEGAUDIT.json
    python tools/segcheck.py --update-lockgraph       # re-pin SEGRACE.json
    python tools/segcheck.py --update-contracts       # re-pin SEGCONTRACT.json
    python tools/segcheck.py --update-failpath        # re-pin SEGFAIL.json

Rules (suppress one finding with `# segcheck: disable=<rule>` on its line):
    import-hygiene        torch/torchvision never import at module scope
    registry-consistency  models/ files <-> MODEL_REGISTRY, classes exist
    trace-purity          no print/np.random/time/datetime in jit'd code
    evidence-citation     measurement claims cite real BENCHMARKS.md
                          headings or committed logs
    obs-purity            no host-side segscope (rtseg_tpu.obs) calls in
                          jit-reachable code
    concurrency           segrace: lock-discipline inference over the
                          threaded serving/obs/warm planes, lock-order
                          graph gated by SEGRACE.json, atomicity lints
                          (lockless +=, check-then-act, notify without
                          the condition, Thread.start publication races)
    contracts             segcontract: cross-plane contract auditor —
                          event schemas (emit sites vs report/live
                          consumers), metric families (registrations vs
                          references incl. CI yaml), wire headers (the
                          serve/headers.py constants; raw X-* literals
                          elsewhere are findings), all pinned in
                          SEGCONTRACT.json
    failpath              segfail: failure-path auditor — silent-death
                          thread entries and swallowing broad excepts
                          (exception-flow), resource release / thread
                          stop / bounded-buffer discipline
                          (resource-lifecycle), and blocking calls
                          under serve/obs hot-plane locks (hot-lock),
                          census pinned in SEGFAIL.json

Audit: jax.eval_shape sweep of every registry model (aux/detail variants
included) asserting the [B, H, W, num_class] eval contract — no weights
materialized, CPU-safe.

Deep audit (--deep, the segaudit family): traces/compiles the real step
artifacts abstractly and checks
    donation              train steps donate the state (and XLA accepts);
                          eval/predict steps donate nothing
    precision-flow        no silent bf16->f32 upcasts outside the
                          sanctioned islands (losses/nn/ops/train)
    collective-budget     compiled data-mesh train-step collective counts
                          == the committed SEGAUDIT.json budget
    dead-param            every param influences the model outputs
                          (--deep-zoo sweeps all registry models)

Exit codes: 0 clean, 1 findings/audit failures, 2 usage or internal error.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rtseg_tpu.analysis.core import ALL_RULES, repo_root, run_lints  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog='segcheck', description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument('--root', default=None,
                    help='repo root (default: auto-detected)')
    ap.add_argument('--rules', default=None,
                    help=f'comma-separated rule subset of {ALL_RULES}')
    ap.add_argument('--lint-only', action='store_true',
                    help='skip the eval_shape zoo audit (no jax import)')
    ap.add_argument('--audit-only', action='store_true',
                    help='run only the eval_shape zoo audit')
    ap.add_argument('--num-class', type=int, default=19,
                    help='audit num_class (default 19, Cityscapes)')
    ap.add_argument('--deep', action='store_true',
                    help='run the jaxpr/HLO deep audits (donation, '
                         'precision-flow, collective-budget, dead-param)')
    ap.add_argument('--deep-models', default='fastscnn',
                    help='comma-separated models for the deep audits '
                         '(default: fastscnn, the flagship artifact)')
    ap.add_argument('--deep-zoo', action='store_true',
                    help='extend the dead-param audit to every registry '
                         'model (minutes of CPU tracing)')
    ap.add_argument('--update-budget', action='store_true',
                    help='rewrite SEGAUDIT.json with the measured '
                         'collective counts instead of gating on them')
    ap.add_argument('--update-lockgraph', action='store_true',
                    help='rewrite SEGRACE.json with the observed lock-'
                         'order graph (after review of a new edge) '
                         'before the lint gate runs; refuses on a cycle')
    ap.add_argument('--update-contracts', action='store_true',
                    help='rewrite SEGCONTRACT.json with the observed '
                         'event/metric/header contract before the lint '
                         'gate runs; refuses while the contract itself '
                         'is incoherent (orphan consumers, unregistered '
                         'metric references, raw X-* literals)')
    ap.add_argument('--update-failpath', action='store_true',
                    help='rewrite SEGFAIL.json with the observed '
                         'failure-path census (entry points, bounded '
                         'buffers, hot locks, suppression budget) '
                         'before the lint gate runs; refuses while the '
                         'tree still has live failure-path findings')
    ap.add_argument('-q', '--quiet', action='store_true',
                    help='print findings only, no summary')
    args = ap.parse_args(argv)
    if args.lint_only and args.audit_only:
        ap.error('--lint-only and --audit-only are mutually exclusive')
    if args.lint_only and args.deep:
        ap.error('--lint-only and --deep are mutually exclusive')
    if args.update_budget and not args.deep:
        ap.error('--update-budget requires --deep')
    if args.update_lockgraph and args.audit_only:
        ap.error('--update-lockgraph is a lint-tier operation; drop '
                 '--audit-only')
    if args.update_contracts and args.audit_only:
        ap.error('--update-contracts is a lint-tier operation; drop '
                 '--audit-only')
    if args.update_failpath and args.audit_only:
        ap.error('--update-failpath is a lint-tier operation; drop '
                 '--audit-only')

    try:
        root = args.root or repo_root()
    except FileNotFoundError as e:
        print(f'segcheck: {e}', file=sys.stderr)
        return 2

    failures = 0
    if args.update_lockgraph:
        # pure-AST, no jax: re-pin the committed lock order, then let the
        # normal lint gate below verify the tree against it
        from rtseg_tpu.analysis.concurrency import update_lockgraph
        try:
            data = update_lockgraph(root)
        except ValueError as e:          # cyclic graph: nothing written
            print(f'segcheck: {e}', file=sys.stderr)
            return 1
        if not args.quiet:
            print(f'segcheck: SEGRACE.json re-pinned '
                  f'({len(data["locks"])} locks, '
                  f'{len(data["edges"])} edges)')
    if args.update_contracts:
        # pure-AST, no jax: re-pin the cross-plane contract, then let
        # the normal lint gate below verify the tree against it
        from rtseg_tpu.analysis.contracts import update_contracts
        try:
            data = update_contracts(root)
        except ValueError as e:          # incoherent: nothing written
            print(f'segcheck: {e}', file=sys.stderr)
            return 1
        if not args.quiet:
            print(f'segcheck: SEGCONTRACT.json re-pinned '
                  f'({len(data["events"])} event types, '
                  f'{len(data["metrics"])} metric families, '
                  f'{len(data["headers"])} headers)')
    if args.update_failpath:
        # pure-AST, no jax: re-pin the failure-path census, then let
        # the normal lint gate below verify the tree against it
        from rtseg_tpu.analysis.failpath import update_failpath
        try:
            data = update_failpath(root)
        except ValueError as e:          # live findings: nothing written
            print(f'segcheck: {e}', file=sys.stderr)
            return 1
        if not args.quiet:
            print(f'segcheck: SEGFAIL.json re-pinned '
                  f'({len(data["entry_points"])} entry points, '
                  f'{len(data["bounded"])} bounded buffer sites, '
                  f'{len(data["hot_locks"])} hot locks, '
                  f'{sum(data["suppressions"].values())} '
                  f'suppressions)')
    if not args.audit_only:
        rules = [r.strip() for r in args.rules.split(',')] \
            if args.rules else None
        try:
            findings = run_lints(root, rules)
        except ValueError as e:
            print(f'segcheck: {e}', file=sys.stderr)
            return 2
        for f in findings:
            print(f)
        failures += len(findings)
        if not args.quiet:
            n = len(findings)
            print(f'segcheck lint: {n} finding(s)'
                  f' across {len(rules or ALL_RULES)} rule(s)')

    if not args.lint_only:
        # deferred import: the lint half must work without jax installed.
        # The audit needs no accelerator (eval_shape is pure tracing), so
        # default to CPU — and pin it through jax.config too, because the
        # axon sitecustomize overrides JAX_PLATFORMS at interpreter start
        # (same counter-override as tests/conftest.py)
        os.environ.setdefault('JAX_PLATFORMS', 'cpu')
        if args.deep:
            # the collective audit needs a real data mesh: force the
            # 8-device virtual CPU platform (same strategy as
            # tests/conftest.py) before any backend initializes
            flags = os.environ.get('XLA_FLAGS', '')
            if '--xla_force_host_platform_device_count' not in flags:
                os.environ['XLA_FLAGS'] = (
                    flags + ' --xla_force_host_platform_device_count=8'
                ).strip()
        import jax
        try:
            jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])
        except Exception:
            pass
        from rtseg_tpu.analysis.shape_audit import audit_zoo
        report = audit_zoo(num_class=args.num_class)
        bad = [r for r in report if not r.ok]
        for r in bad:
            print(f'audit: {r}')
        failures += len(bad)
        if not args.quiet:
            print(f'segcheck audit: {len(report) - len(bad)}/{len(report)} '
                  f'zoo variants pass the shape/dtype contract')

    if args.deep:
        from rtseg_tpu.analysis import (audit_collective_budget,
                                        audit_dead_params, audit_donation,
                                        audit_quant_boundaries,
                                        audit_train_precision)
        from rtseg_tpu.analysis.step_harness import build_step_artifacts
        models = [m.strip() for m in args.deep_models.split(',')
                  if m.strip()]
        deep_findings = []
        for name in models:
            # ONE build + abstract lowering of the data-mesh train step
            # feeds donation intent, the precision trace, and (via one XLA
            # compile) donation acceptance + the collective budget; the
            # audited builder/mesh matrix itself lives in audit_donation
            art = build_step_artifacts(kind='train', model_name=name)
            lowered = art.lower()
            compiled_text = lowered.compile().as_text()
            deep_findings += audit_donation(
                model_name=name, compiled_text=compiled_text,
                train_artifact=art, train_lowered=lowered)
            deep_findings += audit_train_precision(model_name=name,
                                                   root=root, artifact=art)
            deep_findings += audit_collective_budget(
                root=root, compiled_text=compiled_text,
                update=args.update_budget, model_name=name)
            # quant-boundary: trace the same model's int8 inference
            # forward and gate its dequant sites (count pinned in
            # SEGAUDIT.json quant_dequant, re-pinned by --update-budget)
            deep_findings += audit_quant_boundaries(
                root=root, update=args.update_budget, model_name=name)
        deep_findings += audit_dead_params(
            model_names=None if args.deep_zoo else models)
        for f in deep_findings:
            print(f)
        failures += len(deep_findings)
        if not args.quiet:
            scope = 'full zoo' if args.deep_zoo else ','.join(models)
            print(f'segcheck deep: {len(deep_findings)} finding(s) '
                  f'(donation, precision-flow, collective-budget, '
                  f'dead-param, quant-boundary; {scope})')

    return 1 if failures else 0


if __name__ == '__main__':
    sys.exit(main())
