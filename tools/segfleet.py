#!/usr/bin/env python
"""segfleet — multi-replica serving fleet CLI (rtseg_tpu/fleet/).

Usage:
    # one front door over N warm-started replica processes per model
    python tools/segfleet.py serve --models seg=fastscnn:2 \
        --num_class 19 --buckets 512x1024,256x512 --batch 8 \
        --compile-cache /var/cache/segwarm --port 8080

    # multi-model tenancy: several groups behind one router
    python tools/segfleet.py serve \
        --models fast=fastscnn:2,bise=bisenetv2:1 ...

    # metrics-driven autoscaling between --models N and --max-replicas
    python tools/segfleet.py serve --models seg=fastscnn:1 \
        --autoscale --max-replicas 4 --p99-high-ms 500 ...

    # the fleet e2e gate (CI + BENCHMARKS.md "Fleet serving
    # methodology"): 2 warm replicas behind the router; baseline one
    # replica's capacity, drive the fleet open-loop, SIGKILL a replica
    # mid-bench (retries must absorb it: 0 errors), drain one mid-burst
    # (0 drops), reconcile router-vs-replica /metrics exactly, then
    # (phase D, segtail) trigger flight dumps on the router + replicas,
    # reconcile the router ring against the loadgen's slowest trace
    # ids, and assemble one cross-plane trace timeline that sums to e2e
    python tools/segfleet.py bench --replicas 2 --buckets 64x64 \
        --batch 4 --check

Replicas are real `tools/segserve.py serve` subprocesses (ephemeral
ports via --port-file, every response tagged X-Replica-Id), spawned
through a shared segwarm compile cache so the second-and-later replicas
start without compiling. The router exposes /predict (+ /predict/<model>
and X-Model), /healthz, /stats, /metrics; replica lifecycle and scaling
land as `fleet` events in the segscope sink (--obs-dir).

Exit codes: 0 ok, 1 --check failed, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rtseg_tpu import obs                                      # noqa: E402
from rtseg_tpu.fleet import (Autoscaler, AutoscalePolicy,      # noqa: E402
                             FleetManager, ReplicaGroup, get_policy,
                             make_router)
from rtseg_tpu.obs.live import parse_prometheus                # noqa: E402
from rtseg_tpu.serve import (bench_http, check_report,         # noqa: E402
                             encode_png, format_report, parse_buckets,
                             synth_images)

_SEGSERVE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         'segserve.py')


# ------------------------------------------------------------------ plumbing
def parse_models(spec: str) -> list:
    """'fast=fastscnn:2,bise=bisenetv2:1' -> [(alias, model, n), ...].
    The replica count defaults to 1; the alias defaults to the model."""
    out = []
    for part in spec.split(','):
        part = part.strip()
        if not part:
            continue
        alias, eq, rest = part.partition('=')
        if not eq:
            alias, rest = part, part
        model, colon, n = rest.partition(':')
        out.append((alias.strip(), model.strip(),
                    int(n) if colon else 1))
    if not out:
        raise ValueError(f'no models in spec {spec!r}')
    return out


def make_spawn_cmd(args, model: str, obs_root=None):
    """argv builder handed to the ReplicaGroup: each replica is a real
    segserve process on an ephemeral port, warm through the shared
    compile cache."""
    def cmd(rid: str, port_file: str):
        argv = [sys.executable, _SEGSERVE, 'serve',
                '--model', model,
                '--num_class', str(args.num_class),
                '--buckets', args.buckets,
                '--batch', str(args.batch),
                '--max-wait-ms', str(args.max_wait_ms),
                '--max-queue', str(args.max_queue),
                '--workers', str(args.workers),
                '--host', '127.0.0.1', '--port', '0',
                '--port-file', port_file,
                '--replica-id', rid]
        if args.compute_dtype:
            argv += ['--compute_dtype', args.compute_dtype]
        if args.compile_cache:
            argv += ['--compile-cache', args.compile_cache]
        if args.ckpt:
            argv += ['--ckpt', args.ckpt]
        if obs_root:
            argv += ['--obs-dir', os.path.join(obs_root,
                                               f'replica-{rid}')]
        return argv
    return cmd


def _scrape(url: str) -> dict:
    import urllib.request
    with urllib.request.urlopen(url + '/metrics', timeout=10) as r:
        return parse_prometheus(r.read().decode())


def _replica_ok_sum(replicas) -> int:
    from rtseg_tpu.obs.live import scrape_counter_sum
    return scrape_counter_sum([r.url for r in replicas],
                              'serve_requests_total', status='ok')


def _router_counts(url: str, group: str) -> dict:
    parsed = _scrape(url)
    return {lab['status']: int(v)
            for lab, v in parsed.get('fleet_requests_total', ())
            if lab.get('group') == group}


def _start_router(groups, args):
    router = make_router(groups, host=args.host, port=args.port,
                         policy=get_policy(args.policy),
                         max_outstanding=args.max_outstanding)
    threading.Thread(target=router.serve_forever, daemon=True).start()
    host, port = router.server_address[:2]
    return router, f'http://{host}:{port}'


# -------------------------------------------------------------------- serve
def cmd_serve(args) -> int:
    sink = None
    if args.obs_dir:
        sink = obs.init_run(args.obs_dir, meta={
            'fleet': True, 'models': args.models,
            'buckets': args.buckets, 'batch': args.batch})
        obs.set_sink(sink)
    specs = parse_models(args.models)
    groups = []
    for alias, model, n in specs:
        groups.append(ReplicaGroup(
            alias, make_spawn_cmd(args, model, obs_root=args.obs_dir),
            min_replicas=n,
            max_replicas=max(n, args.max_replicas or n)))
    manager = FleetManager(groups, run_dir=args.run_dir,
                           max_restarts=args.max_restarts,
                           drain_grace_s=args.drain_grace_s)
    manager.start()
    scalers = []
    router = None
    try:
        for g in groups:
            reps = manager.wait_ready(g.name,
                                      timeout_s=args.ready_timeout_s)
            times = ', '.join(f'{r.replica_id} {r.ready_s:.2f}s'
                              for r in reps)
            print(f'segfleet: group {g.name} ready ({times})',
                  flush=True)
        router, url = _start_router({g.name: g for g in groups}, args)
        if args.autoscale:
            policy = AutoscalePolicy(
                p99_high_ms=args.p99_high_ms,
                p99_low_ms=args.p99_low_ms,
                queue_high=args.queue_high,
                cooldown_s=args.cooldown_s)
            for g in groups:
                s = Autoscaler(manager, g.name, policy=policy,
                               poll_s=args.autoscale_poll_s)
                s.start()
                scalers.append(s)
        names = ','.join(g.name for g in groups)
        print(f'segfleet: router on {url} | groups {names} | policy '
              f'{args.policy} | POST /predict[/<model>], GET /healthz '
              f'/stats /metrics'
              + (' | autoscaling' if scalers else ''), flush=True)
        # serve until SIGTERM/SIGINT, then drain the whole fleet
        done = threading.Event()
        signal.signal(signal.SIGTERM, lambda *a: done.set())
        try:
            done.wait()
        except KeyboardInterrupt:
            pass
        print('segfleet: draining fleet...', flush=True)
    finally:
        for s in scalers:
            s.stop()
        if router is not None:
            router.shutdown()
        manager.stop(drain=True, timeout_s=args.drain_grace_s)
        if sink is not None:
            sink.emit({'event': 'run_end'})
            sink.close()
            if obs.get_sink() is sink:
                obs.set_sink(None)
    return 0


# -------------------------------------------------------------------- bench
def _bench_thread(url, payloads, requests, rps, seed, box, key):
    box[key] = bench_http(url, payloads, requests, rps, seed=seed)


def cmd_bench(args) -> int:
    obs_dir = args.obs_dir or '/tmp/segfleet_bench/segscope'
    sink = obs.init_run(obs_dir, meta={
        'fleet': True, 'bench': True, 'model': args.model,
        'buckets': args.buckets, 'batch': args.batch,
        'replicas': args.replicas})
    obs.set_sink(sink)
    args.models = f'fleet={args.model}:{args.replicas}'
    # min starts at 1: the first replica populates the shared segwarm
    # cache, then scale_to fans out the rest as warm starts — the
    # spin-up numbers in the report show the cold/warm split honestly.
    # obs_root gives every replica its own sink subdir so phase D can
    # assemble a cross-plane trace over the whole fleet obs root.
    group = ReplicaGroup('fleet',
                         make_spawn_cmd(args, args.model,
                                        obs_root=obs_dir),
                         min_replicas=1,
                         max_replicas=args.replicas)
    manager = FleetManager([group], run_dir=args.run_dir,
                           drain_grace_s=args.drain_grace_s)
    buckets = parse_buckets(args.buckets)
    payloads = [encode_png(im)
                for im in synth_images(buckets, seed=args.seed)]
    problems = []
    report = {'buckets': args.buckets, 'batch': args.batch,
              'replicas': args.replicas}
    router = None
    t_start = time.perf_counter()
    try:
        # ---- spin-up: first replica fills the shared compile cache,
        # the rest warm-start from it
        manager.start()
        manager.wait_ready('fleet', 1, timeout_s=args.ready_timeout_s)
        if args.replicas > 1:
            manager.scale_to('fleet', args.replicas,
                             reason='bench spin-up')
        replicas = manager.wait_ready('fleet', args.replicas,
                                      timeout_s=args.ready_timeout_s)
        report['spinup'] = {r.replica_id: round(r.ready_s, 2)
                            for r in replicas}
        print(f'segfleet bench — {args.replicas}x {args.model} '
              f'{args.buckets} batch {args.batch} | spin-up '
              + ' '.join(f'{k}={v}s'
                         for k, v in report['spinup'].items()),
              flush=True)
        router, url = _start_router({'fleet': group}, args)
        print(f'  router         : {url} | policy {args.policy}',
              flush=True)

        # ---- phase 0: single-replica capacity (closed gate not applied;
        # overload on purpose so ok/wall measures capacity, not the
        # arrival schedule)
        base = bench_http(replicas[0].url, payloads,
                          args.baseline_requests, args.overload_rps,
                          seed=args.seed)
        c1 = base['rps_achieved']
        report['baseline'] = base
        print(f'  baseline       : 1 replica serves {c1:.1f} rps at '
              f'saturation ({base["ok"]}/{base["requests"]} ok under '
              f'{args.overload_rps} rps overload)', flush=True)

        # ---- phase A: the fleet sustains > 1x single-replica capacity
        # with zero losses; reconcile router vs replicas vs client
        fleet_rps = args.fleet_rps or round(
            max(8.0, args.target_speedup * c1), 1)
        before_rep = _replica_ok_sum(replicas)
        before_rtr = _router_counts(url, 'fleet').get('ok', 0)
        phase_a = bench_http(url, payloads, args.requests, fleet_rps,
                             seed=args.seed + 1)
        report['fleet'] = phase_a
        speedup = (phase_a['rps_achieved'] / c1) if c1 else 0.0
        report['speedup_vs_single'] = round(speedup, 2)
        print(format_report(phase_a), flush=True)
        print(f'  vs 1 replica   : {phase_a["rps_achieved"]:.1f} rps '
              f'over {c1:.1f} -> {speedup:.2f}x', flush=True)
        problems += check_report(phase_a, args.p95_ms,
                                 expect_replicas=args.replicas)
        if speedup < args.min_speedup:
            problems.append(f'fleet speedup {speedup:.2f}x < '
                            f'--min-speedup {args.min_speedup}x')
        after_rep = _replica_ok_sum(replicas)
        after_rtr = _router_counts(url, 'fleet').get('ok', 0)
        recon = {'loadgen_ok': phase_a['ok'],
                 'router_ok_delta': after_rtr - before_rtr,
                 'replica_ok_delta': after_rep - before_rep}
        report['reconciliation'] = recon
        if len(set(recon.values())) != 1:
            problems.append(f'/metrics reconciliation mismatch: {recon}')
        print(f'  reconciliation : loadgen {recon["loadgen_ok"]} == '
              f'router {recon["router_ok_delta"]} == replicas '
              f'{recon["replica_ok_delta"]}', flush=True)

        # ---- phase B: SIGKILL a replica mid-bench; the router's retry
        # absorbs the in-flight casualties and the manager restarts it
        kill_rps = args.kill_rps or round(max(4.0, 0.5 * c1), 1)
        box = {}
        t = threading.Thread(target=_bench_thread, args=(
            url, payloads, args.kill_requests, kill_rps,
            args.seed + 2, box, 'r'))
        t.start()
        time.sleep((args.kill_requests / kill_rps) / 3)
        victim = replicas[1]
        os.kill(victim.pid, signal.SIGKILL)
        t.join(timeout=300)
        phase_b = box['r']
        report['kill'] = phase_b
        print(f'  kill mid-bench : SIGKILL {victim.replica_id} at 1/3 '
              f'of {args.kill_requests} reqs @ {kill_rps} rps -> '
              f'{phase_b["ok"]} ok | {phase_b["errors"]} errors | '
              f'{phase_b.get("rejected", 0)} rejected', flush=True)
        if phase_b['errors'] or phase_b['ok'] != args.kill_requests:
            problems.append(
                f'kill phase lost requests: {phase_b["ok"]}/'
                f'{args.kill_requests} ok, {phase_b["errors"]} errors')
        deadline = time.monotonic() + args.ready_timeout_s
        while victim.state != 'ready' and time.monotonic() < deadline:
            time.sleep(0.1)
        report['victim_restarted'] = victim.state == 'ready'
        print(f'  restart        : {victim.replica_id} '
              f'{"back ready" if report["victim_restarted"] else "NOT ready"}'
              f' (restarts={victim.restarts})', flush=True)
        if not report['victim_restarted']:
            problems.append('killed replica was not restarted in time')

        # ---- phase C: drain a replica mid-burst; zero in-flight drops
        drain_rps = args.drain_rps or round(max(4.0, 0.4 * c1), 1)
        box = {}
        t = threading.Thread(target=_bench_thread, args=(
            url, payloads, args.drain_requests, drain_rps,
            args.seed + 3, box, 'r'))
        t.start()
        time.sleep((args.drain_requests / drain_rps) / 3)
        drained = replicas[0]
        manager.drain_replica('fleet', drained.replica_id,
                              reason='bench drain phase')
        t.join(timeout=300)
        phase_c = box['r']
        report['drain'] = phase_c
        deadline = time.monotonic() + args.drain_grace_s + 10
        while drained.state != 'stopped' \
                and time.monotonic() < deadline:
            time.sleep(0.1)
        exit_code = drained.poll_exit()
        report['drain_exit_code'] = exit_code
        print(f'  drain mid-burst: {drained.replica_id} drained -> '
              f'exit {exit_code} | burst {phase_c["ok"]}/'
              f'{args.drain_requests} ok | {phase_c["errors"]} errors',
              flush=True)
        if phase_c['errors'] or phase_c['ok'] != args.drain_requests:
            problems.append(
                f'drain phase dropped in-flight work: {phase_c["ok"]}/'
                f'{args.drain_requests} ok, {phase_c["errors"]} errors')
        if exit_code != 0:
            problems.append(f'drained replica exit code {exit_code} '
                            f'(want 0)')

        # ---- phase D: segtail flight forensics — drive a light burst
        # with zero client-visible errors, trigger a flight dump on the
        # router and every live replica, reconcile the router's dumped
        # records against the loadgen's slowest trace ids, then prove
        # the slowest request assembles into a cross-plane timeline
        # whose rows sum exactly to the router-recorded e2e
        from rtseg_tpu.obs.live import trigger_flight
        from rtseg_tpu.obs.trail import assemble, load_trace
        d_rps = args.drain_rps or round(max(4.0, 0.4 * c1), 1)
        phase_d = bench_http(url, payloads, args.flight_requests,
                             d_rps, seed=args.seed + 4)
        report['flight_bench'] = phase_d
        if phase_d['errors'] or phase_d['ok'] != args.flight_requests:
            problems.append(
                f'flight phase not clean: {phase_d["ok"]}/'
                f'{args.flight_requests} ok, '
                f'{phase_d["errors"]} errors')
        live = [r for r in replicas if r.state == 'ready']
        dumps = []
        for u in [url] + [r.url for r in live]:
            try:
                dumps.append(trigger_flight(u,
                                            reason='bench_forensics'))
            except OSError as e:
                problems.append(f'flight trigger {u}: {e}')
        report['flight'] = {
            'dumps': len(dumps),
            'records': sum(d.get('records', 0) for d in dumps),
            'sources': sorted({str(d.get('source')) for d in dumps})}
        print(f'  flight dumps   : {len(dumps)} '
              f'({", ".join(report["flight"]["sources"])}) — '
              f'{report["flight"]["records"]} records after a clean '
              f'{phase_d["ok"]}/{args.flight_requests} burst',
              flush=True)
        if not dumps:
            problems.append('no flight dump answered the trigger')
        # every phase-D slowest trace id must be in the router's ring:
        # the 512-slot ring holds more than every request the router
        # has forwarded this bench (phases A-D total < 512)
        slowest = phase_d.get('slowest') or []
        router_dump = next((d for d in dumps
                            if d.get('source') == 'router'), None)
        dumped_tids = {r.get('trace_id') for r in
                       (router_dump or {}).get('dump_records', ())}
        missing = [s['trace_id'] for s in slowest
                   if s.get('trace_id') not in dumped_tids]
        if router_dump is None:
            problems.append('router answered no flight dump')
        elif missing:
            problems.append(f'flight ring missing loadgen slowest '
                            f'trace ids: {missing}')
        else:
            print(f'  flight recon   : all {len(slowest)} slowest '
                  f'loadgen trace ids present in the router dump '
                  f'({len(dumped_tids)} ring records)', flush=True)
        if slowest:
            tid = slowest[0]['trace_id']
            tl = assemble(load_trace([obs_dir], tid), tid)
            if tl is None:
                problems.append(f'segscope trace: no timeline for '
                                f'slowest trace id {tid}')
            else:
                rows_ms = sum(r['ms'] for r in tl['rows'])
                gap = abs(rows_ms - tl['e2e_ms'])
                report['trace'] = {
                    'trace_id': tid, 'anchor': tl['anchor'],
                    'e2e_ms': tl['e2e_ms'],
                    'rows': len(tl['rows']),
                    'residue_ms': tl['residue_ms'],
                    'sources': tl['sources']}
                print(f'  trace timeline : {tid} — {len(tl["rows"])} '
                      f'rows sum {rows_ms:.3f}ms == anchor '
                      f'{tl["anchor"]} e2e {tl["e2e_ms"]:.3f}ms '
                      f'across {len(tl["sources"])} sinks', flush=True)
                if gap > 0.01:
                    problems.append(
                        f'trace rows sum {rows_ms:.3f} != e2e '
                        f'{tl["e2e_ms"]:.3f} for {tid}')
                if len(tl['sources']) < 2:
                    problems.append(
                        f'trace {tid} did not span router + replica '
                        f'sinks: {tl["sources"]}')
    finally:
        if router is not None:
            router.shutdown()
        manager.stop(drain=False)
        sink.emit({'event': 'run_end'})
        sink.close()
        if obs.get_sink() is sink:
            obs.set_sink(None)

    # ---- fleet events: the sink must carry the scaling/lifecycle story
    events = []
    for name in sorted(os.listdir(obs_dir)):
        if name.startswith('events-') and name.endswith('.jsonl'):
            with open(os.path.join(obs_dir, name)) as f:
                events += [json.loads(line) for line in f if line.strip()]
    actions = [e['action'] for e in events if e.get('event') == 'fleet']
    report['fleet_events'] = {a: actions.count(a) for a in sorted(set(
        actions))}
    report['wall_s'] = round(time.perf_counter() - t_start, 1)
    print(f'  fleet events   : {report["fleet_events"]} '
          f'(sink {obs_dir})', flush=True)
    if not any(a in actions for a in ('scale_up', 'scale_down',
                                      'replica_death')):
        problems.append('no fleet scale/death event reached the sink')
    if args.report_json:
        with open(args.report_json, 'w') as f:
            json.dump(report, f, indent=2)
    if args.check:
        if problems:
            print('segfleet check FAILED: ' + '; '.join(problems),
                  file=sys.stderr, flush=True)
            return 1
        print(f'segfleet check OK: {args.replicas} replicas | phase A '
              f'{report["fleet"]["ok"]}/{args.requests} ok at '
              f'{report["speedup_vs_single"]}x single-replica | kill '
              f'absorbed {report["kill"]["ok"]}/{args.kill_requests} | '
              f'drain clean {report["drain"]["ok"]}/'
              f'{args.drain_requests}, exit 0 | exact /metrics '
              f'reconciliation | flight '
              f'{report.get("flight", {}).get("dumps", 0)} dumps, '
              f'trace rows == e2e | {report["wall_s"]}s', flush=True)
    return 0


# --------------------------------------------------------------------- main
def _add_engine_args(p) -> None:
    p.add_argument('--model', default='fastscnn')
    p.add_argument('--num_class', type=int, default=19)
    p.add_argument('--compute_dtype', default=None)
    p.add_argument('--ckpt', default=None)
    p.add_argument('--buckets', default='512x1024')
    p.add_argument('--batch', type=int, default=8)
    p.add_argument('--max-wait-ms', type=float, default=5.0)
    p.add_argument('--max-queue', type=int, default=128)
    p.add_argument('--workers', type=int, default=2)
    p.add_argument('--compile-cache', default=None, metavar='DIR',
                   help='shared segwarm cache: replica 1 compiles, '
                        'every later spawn deserializes')


def _add_fleet_args(p) -> None:
    p.add_argument('--host', default='127.0.0.1')
    p.add_argument('--port', type=int, default=8080)
    p.add_argument('--policy', default='least-outstanding',
                   choices=('least-outstanding', 'round-robin'))
    p.add_argument('--max-outstanding', type=int, default=256,
                   help='fleet-level admission bound per group')
    p.add_argument('--run-dir', default=None,
                   help='port files + per-replica logs land here')
    p.add_argument('--ready-timeout-s', type=float, default=600.0)
    p.add_argument('--drain-grace-s', type=float, default=60.0)
    p.add_argument('--max-restarts', type=int, default=5)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog='segfleet', description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest='cmd', required=True)

    sp = sub.add_parser('serve', help='run the fleet behind one router')
    _add_engine_args(sp)
    _add_fleet_args(sp)
    sp.add_argument('--models', default='seg=fastscnn:1',
                    help='alias=model:replicas[,alias=model:replicas...]')
    sp.add_argument('--autoscale', action='store_true')
    sp.add_argument('--max-replicas', type=int, default=None,
                    help='autoscale ceiling (default: the --models count)')
    sp.add_argument('--p99-high-ms', type=float, default=1000.0)
    sp.add_argument('--p99-low-ms', type=float, default=200.0)
    sp.add_argument('--queue-high', type=float, default=4.0)
    sp.add_argument('--cooldown-s', type=float, default=10.0)
    sp.add_argument('--autoscale-poll-s', type=float, default=2.0)
    sp.add_argument('--obs-dir', default=None)

    bp = sub.add_parser('bench', help='the fleet e2e gate (see docstring)')
    _add_engine_args(bp)
    _add_fleet_args(bp)
    bp.add_argument('--replicas', type=int, default=2)
    bp.add_argument('--requests', type=int, default=192,
                    help='phase A open-loop request count')
    bp.add_argument('--baseline-requests', type=int, default=128)
    bp.add_argument('--overload-rps', type=float, default=300.0,
                    help='baseline saturation rate (capacity probe)')
    bp.add_argument('--fleet-rps', type=float, default=None,
                    help='phase A arrival rate (default: '
                         '--target-speedup x measured single capacity)')
    bp.add_argument('--target-speedup', type=float, default=1.6)
    bp.add_argument('--min-speedup', type=float, default=1.5,
                    help='--check gate on fleet throughput vs one '
                         'replica')
    bp.add_argument('--kill-requests', type=int, default=96)
    bp.add_argument('--kill-rps', type=float, default=None,
                    help='phase B rate (default: 0.5 x probed capacity)')
    bp.add_argument('--drain-requests', type=int, default=64)
    bp.add_argument('--drain-rps', type=float, default=None,
                    help='phase C rate (default: 0.4 x probed capacity)')
    bp.add_argument('--flight-requests', type=int, default=48,
                    help='phase D (segtail forensics) burst size; keep '
                         'phases A-D under the 512-slot flight ring so '
                         'the dump-vs-loadgen reconciliation is exact')
    bp.add_argument('--p95-ms', type=float, default=5000.0)
    bp.add_argument('--seed', type=int, default=0)
    bp.add_argument('--obs-dir', default=None)
    bp.add_argument('--report-json', default=None, metavar='PATH')
    bp.add_argument('--check', action='store_true')

    args = ap.parse_args(argv)
    return cmd_serve(args) if args.cmd == 'serve' else cmd_bench(args)


if __name__ == '__main__':
    sys.exit(main())
