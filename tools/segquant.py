#!/usr/bin/env python
"""segquant — post-training int8 quantization report for the model zoo.

For each model: quantize the weights per-channel symmetric int8
(rtseg_tpu/quant/ptq.py), run the deterministic calibration forward
(rtseg_tpu/quant/calibrate.py) over a seeded sample slice, and print the
evidence — weight bytes f32 vs int8, f32-vs-int8 argmax agreement, mIoU
delta, and the max-drop gate verdict. The same machinery `segship bake
--quant int8` runs; this tool answers "is this model quantizable?"
before anything ships.

    # synthetic calibration slice (seeded, through the serving preprocess)
    python tools/segquant.py --models fastscnn,bisenetv2 --samples 8

    # real eval slice from a segpipe PackedCache (ground-truth mIoU)
    python tools/segquant.py --models fastscnn \
        --calib-cache /data/cache/cityscapes-val-... --samples 16

    # write the flagship QuantRecord for inspection / diffing
    python tools/segquant.py --models fastscnn --out /tmp/QUANT.json

`--activations` additionally calibrates per-tensor activation scales and
quantizes the input boundary (QDQ), matching `bake --quant-activations`.
Determinism contract: same models + samples + seed (+ cache) ⇒
byte-identical records (tests/test_segquant.py pins this).

Exit codes: 0 every model passed its gate, 1 any gate failure or model
error, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_MODELS = 'fastscnn'


def _calibration_slice(cfg, args, buckets):
    """(images, masks, source, indices) — the same two sources bake_model
    uses: a segpipe PackedCache (eval suffix applied, ground truth) or a
    seeded synthetic batch through the real serving preprocess."""
    import numpy as np
    from rtseg_tpu.quant import select_calibration_indices
    from rtseg_tpu.serve import encode_png, make_preprocess, synth_images

    if args.calib_cache:
        from rtseg_tpu.data.segpipe.cache import PackedCache
        from rtseg_tpu.data.transforms import EvalTransform
        cache = PackedCache(args.calib_cache)
        indices = select_calibration_indices(len(cache), args.samples,
                                             seed=args.seed)
        tf = EvalTransform(cfg)
        pairs = [tf.suffix(np.asarray(img), np.asarray(msk))
                 for img, msk in (cache.read(i) for i in indices)]
        name = os.path.basename(os.path.normpath(args.calib_cache))
        return (np.stack([p[0] for p in pairs]),
                np.stack([p[1] for p in pairs]),
                f'segpipe:{name}', indices)
    preprocess = make_preprocess(cfg)
    raws = synth_images([buckets[0]], seed=args.seed,
                        per_shape=max(1, args.samples))
    return (np.stack([preprocess(encode_png(im)) for im in raws]),
            None, 'synthetic', None)


def quantize_one(name: str, args):
    """Quantize + calibrate one zoo model; returns the QuantRecord."""
    import jax
    import jax.numpy as jnp
    from rtseg_tpu.config import SegConfig
    from rtseg_tpu.models import get_model
    from rtseg_tpu.quant import calibrate, quantize_variables

    cfg = SegConfig(dataset='synthetic', model=name,
                    num_class=args.num_class,
                    compute_dtype=args.compute_dtype,
                    save_dir='/tmp/segquant_cli', use_tb=False)
    cfg.resolve(num_devices=1)
    net = get_model(cfg)
    variables = net.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 64, 64, 3), jnp.float32), False)
    if args.ckpt:
        from rtseg_tpu.train.checkpoint import restore_weights
        p, bs = restore_weights(args.ckpt, variables['params'],
                                variables.get('batch_stats', {}))
        variables = dict(variables, params=p, batch_stats=bs)
    qvariables = quantize_variables(variables)
    buckets = [(args.imgh, args.imgw)]
    images, masks, source, indices = _calibration_slice(cfg, args,
                                                        buckets)
    return calibrate(net, variables, qvariables, images, masks,
                     compute_dtype=cfg.compute_dtype,
                     num_class=args.num_class, max_drop=args.max_drop,
                     activations=args.activations, source=source,
                     seed=args.seed, indices=indices)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog='segquant', description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument('--models', default=DEFAULT_MODELS,
                    help='comma-separated registry models')
    ap.add_argument('--num-class', type=int, default=19)
    ap.add_argument('--compute-dtype', default='float32')
    ap.add_argument('--imgh', type=int, default=256)
    ap.add_argument('--imgw', type=int, default=512)
    ap.add_argument('--samples', type=int, default=8,
                    help='calibration sample count (seeded selection)')
    ap.add_argument('--seed', type=int, default=0)
    ap.add_argument('--max-drop', type=float, default=0.05,
                    help='mIoU-drop gate per model (vs ground truth '
                         'with --calib-cache, vs the f32 forward '
                         'otherwise — the record labels which)')
    ap.add_argument('--activations', action='store_true',
                    help='also calibrate activation scales + quantize '
                         'the input boundary (QDQ)')
    ap.add_argument('--calib-cache', default=None,
                    help='segpipe PackedCache dir (real samples + '
                         'ground-truth mIoU)')
    ap.add_argument('--ckpt', default=None,
                    help='checkpoint to quantize (default: seeded init '
                         '— structural/agreement evidence only)')
    ap.add_argument('--out', default=None, metavar='PATH',
                    help='write the LAST model\'s QuantRecord here '
                         '(record_to_json canonical bytes)')
    ap.add_argument('--json', action='store_true',
                    help='one full QuantRecord JSON line per model')
    args = ap.parse_args(argv)

    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import jax
    try:
        jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])
    except Exception:   # noqa: BLE001 — backend already initialized
        pass
    from rtseg_tpu.quant import record_to_json

    models = [m.strip() for m in args.models.split(',') if m.strip()]
    if not models:
        print('segquant: no models', file=sys.stderr)
        return 2
    if not args.json:
        print(f'| model | f32 MiB | int8 MiB | quantized leaves | '
              f'agreement | mIoU drop ({("gt" if args.calib_cache else "vs f32")}) '
              f'| gate (<= {args.max_drop}) |')
        print('|---|---|---|---|---|---|---|')
    failures = 0
    record = None
    for name in models:
        try:
            record = quantize_one(name, args)
        except Exception as e:   # noqa: BLE001 — one broken model must
            # not hide the rest of the table; it still fails the run
            failures += 1
            msg = ' '.join(f'{type(e).__name__}: {e}'.split())[:120]
            if args.json:
                print(json.dumps({'model': name, 'error': msg}),
                      flush=True)
            else:
                print(f'| {name} | FAILED: {msg} | — | — | — | — | — |',
                      flush=True)
            continue
        if not record['gate']['passed']:
            failures += 1
        if args.json:
            print(json.dumps({'model': name, **record},
                             sort_keys=True), flush=True)
        else:
            w = record['weights']
            f32_mib = w['f32'] / 2**20
            int8_mib = w['int8'] / 2**20
            print(f'| {name} | {f32_mib:.2f} | {int8_mib:.2f} | '
                  f'{w["quantized_leaves"]}/{w["total_leaves"]} | '
                  f'{record["agreement_frac"]:.4f} | '
                  f'{record["miou"]["drop"]:.4f} | '
                  f'{"PASS" if record["gate"]["passed"] else "FAIL"} |',
                  flush=True)
    if not args.json:
        print(f'\ncalibration: {args.samples} samples, seed {args.seed}, '
              f'{args.imgh}x{args.imgw}, '
              + (f'cache {args.calib_cache}' if args.calib_cache
                 else 'synthetic (mIoU drop is f32-forward-relative)'))
    if args.out and record is not None:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, 'w') as f:
            f.write(record_to_json(record))
        print(f'segquant: record -> {args.out}', flush=True)
    return 1 if failures else 0


if __name__ == '__main__':
    sys.exit(main())
