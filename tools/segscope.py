#!/usr/bin/env python
"""segscope — run-report CLI over the obs/ JSONL telemetry.

Reads the per-host event streams a run wrote under config.obs_dir
(default save_dir/segscope) and prints the step-time/goodput breakdown, or
compares two runs as a regression table. Serving runs (tools/segserve.py
bench --obs-dir) get a serving section — RPS, request p50/p95/p99, stage
means, drop/reject counts, batch occupancy — from their request/batch
events, and `diff` flags serve-p99/RPS regressions alongside the training
rows. segpipe runs add an h2d stage row (host->device transfer seconds;
"overlapped" when data-wait is ~0) and a packed-cache hit-rate line from
the loaders' per-epoch cache events; `diff` marks data-wait/h2d
regressions >5% as REGRESSED. Streaming runs (tools/segstream.py bench
--obs-dir) get a streaming section — frame p50/p99, inter-frame jitter,
freshness (mean mask age), dropped-late/stale counts, keyframe ratio,
session opens/migrations and a provenance breakdown — from their
frame/session/session_migrate events, and `diff`/`live` carry the same
rows (frame p99, jitter, freshness, dropped-late, keyframe ratio) as
REGRESSED-markable gates. Pure stdlib+numpy: works on machines
without jax (e.g. a laptop holding synced run dirs).

Runs with segprof sampled profiling on (`config.profile_every`) or
`/debug/profile` captures get a device section — busy %, per-category
(conv/matmul/collective/copy/fusion/infeed) and per-module device time,
attribution coverage, peak HBM — and `--roofline` (the `tools/roofline.py
--json` output) adds a measured-MFU line: device busy fraction x the
model's analytical ceiling. `diff` grows per-category device regression
rows and `--check` turns any REGRESSED row into exit 1.

Usage:
    python tools/segscope.py report save/segscope
    python tools/segscope.py report save/segscope --json
    python tools/segscope.py report save/segscope --check   # CI gate:
                                        # goodput > 0 and 0 stalls, else 1
    python tools/segscope.py report save/segscope --all-runs
    python tools/segscope.py report save/segscope --roofline roofline.json
    python tools/segscope.py diff runA/segscope runB/segscope [--check]

    # live plane (segtrace): follow a RUNNING system — tail a run's sink
    # dir, or poll a serve replica's /metrics endpoint — and render a
    # refreshing SLO summary
    python tools/segscope.py live save/segscope
    python tools/segscope.py live http://127.0.0.1:8080 --interval 2
    python tools/segscope.py live http://host:8080 --once --check \
        --p99-ms 500                                    # CI gate
    python tools/segscope.py live http://router:8080 --check --p99-ms 200 \
        --flight-on-breach http://router:8080           # breach -> dump

    # segtail: cross-plane forensics for ONE trace id — join the router's
    # hop accounting, the replica's ingress/batch/request events, stream
    # frame events and any flight-recorder snapshots across one or more
    # sink dirs into a causally-ordered, gap-attributed timeline whose
    # rows sum exactly to the recorded e2e (explicit residue row)
    python tools/segscope.py trace 4fe2a1b09c3d5e67 fleet-obs/
    python tools/segscope.py trace <id> router-obs/ replica-obs/ --json

Metric definitions live in rtseg_tpu/obs/report.py and BENCHMARKS.md
("Goodput"). `report` summarizes the segment after the last run_start
marker (resumes append to the same files); `--all-runs` keeps everything.
`live --check` fails on any stall, any request error, p99 over the
--p99-ms threshold, or a target with no observed activity.

Exit codes: 0 ok, 1 --check failed / regression, 2 usage or missing run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rtseg_tpu.obs.live import (MetricsPoller, SinkTailer,    # noqa: E402
                                check_frame, format_frame,
                                trigger_flight)
from rtseg_tpu.obs.report import (diff_rows, diff_table,      # noqa: E402
                                  format_summary, load_events,
                                  load_roofline, summarize)
from rtseg_tpu.obs.trail import (assemble, format_timeline,   # noqa: E402
                                 load_trace)


def _run_live(args) -> int:
    if args.target.startswith(('http://', 'https://')):
        source = MetricsPoller(args.target)
    else:
        source = SinkTailer(args.target, window_s=args.window)
    breach_fired = False
    while True:
        try:
            frame = source.poll()
        except OSError as e:
            print(f'segscope live: {args.target}: {e}', file=sys.stderr)
            return 2
        out = format_frame(frame)
        if args.once:
            print(out)
        else:
            # full-frame repaint: clear + home, like watch(1)
            print('\x1b[2J\x1b[H' + out, flush=True)
        if args.check:
            problems = check_frame(frame, p99_ms=args.p99_ms,
                                   max_hbm_bytes=args.max_hbm_bytes)
            if problems and args.flight_on_breach and not breach_fired:
                # segtail: an SLO breach is the live poller's flight
                # trigger — dump each target's recorder once per breach
                # episode (re-armed when a frame comes back clean)
                breach_fired = True
                for u in args.flight_on_breach:
                    try:
                        dump = trigger_flight(u, reason='slo_breach')
                        print(f'  FLIGHT: dumped {dump.get("records")} '
                              f'records from {u} '
                              f'({dump.get("source")})', flush=True)
                    except OSError as e:
                        print(f'  FLIGHT: {u}: {e}', file=sys.stderr)
            elif not problems:
                breach_fired = False
            if problems:
                # a transient empty first frame is not a failure while
                # following; only --once treats it as terminal
                if args.once:
                    print('segscope live check FAILED: '
                          + '; '.join(problems), file=sys.stderr)
                    return 1
                print('  CHECK: ' + '; '.join(problems), flush=True)
            elif args.once:
                print('segscope live check OK')
        if args.once:
            return 0
        time.sleep(args.interval)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog='segscope', description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest='cmd', required=True)

    rp = sub.add_parser('report', help='summarize one run')
    rp.add_argument('path', help='obs dir (events-*.jsonl) or one file')
    rp.add_argument('--json', action='store_true',
                    help='machine-readable summary')
    rp.add_argument('--all-runs', action='store_true',
                    help='include events before the last run_start')
    rp.add_argument('--check', action='store_true',
                    help='exit 1 unless goodput > 0, stalls == 0 and at '
                         'least one train step was recorded')
    rp.add_argument('--roofline', default=None, metavar='PATH',
                    help='tools/roofline.py --json output; enables the '
                         'measured-MFU line (device busy x analytical '
                         'ceiling) in the device section')

    dp = sub.add_parser('diff', help='compare two runs (A=baseline, B=new)')
    dp.add_argument('a')
    dp.add_argument('b')
    dp.add_argument('--json', action='store_true')
    dp.add_argument('--check', action='store_true',
                    help='exit 1 when any row is REGRESSED (>5% worse; '
                         'includes the segprof per-category device rows)')

    lp = sub.add_parser('live', help='follow a running system (sink dir '
                                     'or /metrics URL)')
    lp.add_argument('target', help='obs dir / events file to tail, or an '
                                   'http(s) URL whose /metrics to poll')
    lp.add_argument('--interval', type=float, default=2.0,
                    help='seconds between frames')
    lp.add_argument('--once', action='store_true',
                    help='render one frame and exit (CI)')
    lp.add_argument('--window', type=float, default=30.0,
                    help='sliding window for sink-mode percentiles/rates')
    lp.add_argument('--check', action='store_true',
                    help='gate: stalls == 0, request errors == 0, some '
                         'activity observed, p99 under --p99-ms')
    lp.add_argument('--p99-ms', type=float, default=None,
                    help='--check request p99 threshold (ms)')
    lp.add_argument('--max-hbm-bytes', type=float, default=None,
                    help='--check peak device memory threshold (bytes, '
                         'from the device_memory_bytes gauges / memory '
                         'events)')
    lp.add_argument('--flight-on-breach', action='append', default=None,
                    metavar='URL',
                    help='POST /debug/flight to this replica/router URL '
                         'when --check detects an SLO breach (repeat for '
                         'several targets; fires once per breach episode)')

    tp = sub.add_parser('trace', help='segtail: cross-plane timeline '
                                      'for one trace id')
    tp.add_argument('trace_id', help='16-hex trace id (from X-Trace-Id, '
                                     'a bench report\'s slowest list, or '
                                     'a p99 exemplar)')
    tp.add_argument('dirs', nargs='+',
                    help='sink dirs to search recursively (a fleet obs '
                         'root covers the router + replica-*/ subdirs)')
    tp.add_argument('--json', action='store_true',
                    help='machine-readable timeline')
    args = ap.parse_args(argv)

    try:
        if args.cmd == 'live':
            try:
                return _run_live(args)
            except KeyboardInterrupt:
                return 0
        if args.cmd == 'trace':
            events = load_trace(args.dirs, args.trace_id)
            tl = assemble(events, args.trace_id) if events else None
            if tl is None:
                print(f'segscope trace: no events carry trace id '
                      f'{args.trace_id} under '
                      + ', '.join(args.dirs), file=sys.stderr)
                return 2
            if args.json:
                print(json.dumps(tl, indent=2, default=str))
            else:
                print(format_timeline(tl))
            return 0
        if args.cmd == 'report':
            events = load_events(args.path, last_run=not args.all_runs)
            roofline = (load_roofline(args.roofline)
                        if args.roofline else None)
            s = summarize(events, roofline=roofline)
            if args.json:
                print(json.dumps(s, indent=2, default=str))
            else:
                print(format_summary(s, args.path))
            if args.check:
                ok = (s['goodput'] > 0 and s['stalls'] == 0
                      and s['train_steps'] > 0)
                if not ok:
                    print(f'segscope check FAILED: '
                          f'goodput={s["goodput"]:.4f} '
                          f'stalls={s["stalls"]} '
                          f'train_steps={s["train_steps"]}',
                          file=sys.stderr)
                    return 1
                print(f'segscope check OK: goodput='
                      f'{100 * s["goodput"]:.1f}% > 0, 0 stalls')
            return 0

        sa = summarize(load_events(args.a))
        sb = summarize(load_events(args.b))
        rows = diff_rows(sa, sb)
        if args.json:
            print(json.dumps({'a': sa, 'b': sb, 'rows': rows},
                             indent=2, default=str))
        else:
            print(f'segscope diff — A: {args.a}  B: {args.b}')
            print(diff_table(sa, sb, rows=rows))
        if args.check:
            regressed = [r for r in rows if r['regressed']]
            if regressed:
                print('segscope diff check FAILED: '
                      + '; '.join(f"{r['label']} {r['a']:.2f} -> "
                                  f"{r['b']:.2f}" for r in regressed),
                      file=sys.stderr)
                return 1
            # stderr under --json: stdout is the machine-readable doc
            print('segscope diff check OK: 0 regressed rows',
                  file=sys.stderr if args.json else sys.stdout)
        return 0
    except FileNotFoundError as e:
        print(f'segscope: {e}', file=sys.stderr)
        return 2


if __name__ == '__main__':
    sys.exit(main())
