#!/usr/bin/env python
"""segscope — run-report CLI over the obs/ JSONL telemetry.

Reads the per-host event streams a run wrote under config.obs_dir
(default save_dir/segscope) and prints the step-time/goodput breakdown, or
compares two runs as a regression table. Serving runs (tools/segserve.py
bench --obs-dir) get a serving section — RPS, request p50/p95/p99, stage
means, drop/reject counts, batch occupancy — from their request/batch
events, and `diff` flags serve-p99/RPS regressions alongside the training
rows. segpipe runs add an h2d stage row (host->device transfer seconds;
"overlapped" when data-wait is ~0) and a packed-cache hit-rate line from
the loaders' per-epoch cache events; `diff` marks data-wait/h2d
regressions >5% as REGRESSED. Pure stdlib+numpy: works on machines
without jax (e.g. a laptop holding synced run dirs).

Usage:
    python tools/segscope.py report save/segscope
    python tools/segscope.py report save/segscope --json
    python tools/segscope.py report save/segscope --check   # CI gate:
                                        # goodput > 0 and 0 stalls, else 1
    python tools/segscope.py report save/segscope --all-runs
    python tools/segscope.py diff runA/segscope runB/segscope

Metric definitions live in rtseg_tpu/obs/report.py and BENCHMARKS.md
("Goodput"). `report` summarizes the segment after the last run_start
marker (resumes append to the same files); `--all-runs` keeps everything.

Exit codes: 0 ok, 1 --check failed / regression, 2 usage or missing run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rtseg_tpu.obs.report import (diff_table, format_summary,  # noqa: E402
                                  load_events, summarize)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog='segscope', description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest='cmd', required=True)

    rp = sub.add_parser('report', help='summarize one run')
    rp.add_argument('path', help='obs dir (events-*.jsonl) or one file')
    rp.add_argument('--json', action='store_true',
                    help='machine-readable summary')
    rp.add_argument('--all-runs', action='store_true',
                    help='include events before the last run_start')
    rp.add_argument('--check', action='store_true',
                    help='exit 1 unless goodput > 0, stalls == 0 and at '
                         'least one train step was recorded')

    dp = sub.add_parser('diff', help='compare two runs (A=baseline, B=new)')
    dp.add_argument('a')
    dp.add_argument('b')
    dp.add_argument('--json', action='store_true')
    args = ap.parse_args(argv)

    try:
        if args.cmd == 'report':
            events = load_events(args.path, last_run=not args.all_runs)
            s = summarize(events)
            if args.json:
                print(json.dumps(s, indent=2, default=str))
            else:
                print(format_summary(s, args.path))
            if args.check:
                ok = (s['goodput'] > 0 and s['stalls'] == 0
                      and s['train_steps'] > 0)
                if not ok:
                    print(f'segscope check FAILED: '
                          f'goodput={s["goodput"]:.4f} '
                          f'stalls={s["stalls"]} '
                          f'train_steps={s["train_steps"]}',
                          file=sys.stderr)
                    return 1
                print(f'segscope check OK: goodput='
                      f'{100 * s["goodput"]:.1f}% > 0, 0 stalls')
            return 0

        sa = summarize(load_events(args.a))
        sb = summarize(load_events(args.b))
        if args.json:
            print(json.dumps({'a': sa, 'b': sb}, indent=2, default=str))
        else:
            print(f'segscope diff — A: {args.a}  B: {args.b}')
            print(diff_table(sa, sb))
        return 0
    except FileNotFoundError as e:
        print(f'segscope: {e}', file=sys.stderr)
        return 2


if __name__ == '__main__':
    sys.exit(main())
