#!/usr/bin/env python
"""segserve — online inference serving CLI (rtseg_tpu/serve/).

Usage:
    # HTTP server: POST an image to /predict, GET /healthz, /stats
    python tools/segserve.py serve --model fastscnn --num_class 19 \
        --ckpt save/best.ckpt --buckets 512x1024,256x512 --batch 8 \
        --port 8080

    # open-loop Poisson load test against an in-process pipeline
    python tools/segserve.py bench --model fastscnn --num_class 19 \
        --buckets 64x64,96x96 --batch 8 --requests 256 --rps 100 --check

    # same, but through a real localhost HTTP server (one process)
    python tools/segserve.py bench ... --via-http

    # against an already-running server
    python tools/segserve.py bench ... --http http://host:8080

Engines load weights from --ckpt (orbax checkpoint) or --artifact
(jax.export StableHLO from tools/export.py); with neither, random init
(load-gen / capacity testing only). --obs-dir writes request/batch events
that `tools/segscope.py report` renders as the serving section.

`bench --check` is the CI gate: exit 1 unless 0 drops, 0 rejections,
0 errors, 0 retraces, one executable per configured bucket, and e2e p95
under --p95-ms.

Exit codes: 0 ok, 1 --check failed, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rtseg_tpu import obs                                      # noqa: E402
from rtseg_tpu.config import SegConfig                         # noqa: E402
from rtseg_tpu.serve import (ServeEngine, ServePipeline,       # noqa: E402
                             bench_http, bench_pipeline,
                             bench_sequential, check_report, encode_png,
                             format_report, make_preprocess, make_server,
                             parse_buckets, synth_images)
from rtseg_tpu.utils import get_colormap                       # noqa: E402


def _add_engine_args(p: argparse.ArgumentParser) -> None:
    p.add_argument('--model', default='fastscnn')
    p.add_argument('--num_class', type=int, default=19)
    p.add_argument('--compute_dtype', default=None,
                   help='forward dtype (default: bfloat16 on TPU-style '
                        'resolve; pass float32 on CPU)')
    p.add_argument('--colormap', default='cityscapes')
    p.add_argument('--ckpt', default=None,
                   help='orbax checkpoint dir to load weights from')
    p.add_argument('--artifact', default=None,
                   help='StableHLO artifact (tools/export.py); bucket and '
                        'batch come from its input shape')
    p.add_argument('--bundle', default=None, metavar='DIR',
                   help='segship ArtifactBundle directory (a published '
                        'registry version — tools/segship.py bake): '
                        'buckets/batch/dtype come from its manifest, the '
                        'engine deserializes its baked executables, and '
                        'every response carries the bundle version in '
                        'X-Artifact-Version')
    p.add_argument('--artifact-version', default=None,
                   help='version stamped into every response as '
                        'X-Artifact-Version (defaults to the --bundle '
                        'manifest version; per-version attribution in '
                        'the load-gen report and the fleet router)')
    p.add_argument('--buckets', default='512x1024',
                   help='comma-separated HxW buckets, e.g. 512x1024,256x512')
    p.add_argument('--batch', type=int, default=8,
                   help='fixed per-executable batch size')
    p.add_argument('--max-wait-ms', type=float, default=5.0,
                   help='batcher coalescing window')
    p.add_argument('--max-queue', type=int, default=128,
                   help='admission bound (requests queued before 503)')
    p.add_argument('--deadline-ms', type=float, default=None,
                   help='per-request queue deadline (drop when exceeded)')
    p.add_argument('--workers', type=int, default=2,
                   help='preprocess / postprocess threads each')
    p.add_argument('--compile-cache', default=None, metavar='DIR',
                   help='segwarm cache dir: persist compiled bucket '
                        'executables (and the XLA compile cache) so the '
                        'next replica starts without compiling')
    p.add_argument('--compile-workers', type=int, default=0,
                   help='bucket-table compile threads (0 = auto)')
    p.add_argument('--no-metrics', action='store_true',
                   help='disable the live metrics registry and request '
                        'tracing (the metrics-off side of the overhead '
                        'A/B, BENCHMARKS.md "Live metrics overhead '
                        'methodology")')


def _build_config(args) -> SegConfig:
    cfg = SegConfig(dataset='synthetic', model=args.model,
                    num_class=args.num_class, colormap=args.colormap,
                    compute_dtype=args.compute_dtype,
                    compile_cache=bool(args.compile_cache),
                    compile_cache_dir=args.compile_cache,
                    compile_workers=args.compile_workers,
                    save_dir='/tmp/segserve', use_tb=False)
    cfg.resolve(num_devices=1)
    if cfg.compile_cache:
        from rtseg_tpu.warm import enable_compile_cache
        enable_compile_cache(cfg)
    return cfg


def _build_engine(args, cfg: SegConfig) -> ServeEngine:
    if args.artifact:
        exe_cache = None
        if cfg.compile_cache:
            from rtseg_tpu.warm import ExeCache
            exe_cache = ExeCache.from_config(cfg)
        return ServeEngine.from_artifact(args.artifact, batch=args.batch,
                                         exe_cache=exe_cache)
    return ServeEngine.from_config(cfg, parse_buckets(args.buckets),
                                   args.batch, ckpt_path=args.ckpt)


def _build_pipeline(args, cfg: SegConfig,
                    engine: ServeEngine) -> ServePipeline:
    from rtseg_tpu.obs.metrics import MetricsRegistry
    return ServePipeline(engine, max_wait_ms=args.max_wait_ms,
                         max_queue=args.max_queue,
                         deadline_ms=args.deadline_ms,
                         preprocess=make_preprocess(cfg),
                         pre_workers=args.workers,
                         post_workers=args.workers,
                         registry=MetricsRegistry(
                             enabled=not args.no_metrics),
                         trace=not args.no_metrics)


def cmd_serve(args) -> int:
    sink = None
    if args.obs_dir:
        # a serving replica can stream its request/batch/ingress events
        # live: `tools/segscope.py live <obs-dir>` tails this sink
        sink = obs.init_run(args.obs_dir, meta={
            'serve': True, 'model': args.model, 'buckets': args.buckets,
            'batch': args.batch})
        obs.set_sink(sink)
    version = args.artifact_version
    if args.bundle:
        # a published segship bundle is self-describing: engine geometry
        # and dtype come from its manifest, the serialized executables
        # deserialize through its own exe/ cache, and the content-hash
        # version attributes every response
        from rtseg_tpu.registry import bundle_serve_config, load_engine
        engine, manifest = load_engine(
            args.bundle, compile_workers=args.compile_workers)
        cfg = bundle_serve_config(manifest)
        args.model = cfg.model
        args.buckets = ','.join(manifest['meta']['buckets'])
        if version is None:
            version = manifest['version']
    else:
        if args.stream and args.cheap_mode == 'light':
            # the light cheap path re-encodes frames through a half-res
            # executable: seal those buckets into the table up front —
            # the table never grows at serve time (retraces=0 gate)
            full = parse_buckets(args.buckets)
            half = [(max(h // 2, 1), max(w // 2, 1)) for h, w in full]
            args.buckets = ','.join(
                f'{h}x{w}' for h, w in dict.fromkeys(full + half))
        cfg = _build_config(args)
        engine = _build_engine(args, cfg)
    pipeline = _build_pipeline(args, cfg, engine)
    stream_config = None
    if args.stream:
        from rtseg_tpu.stream import StreamConfig
        stream_config = StreamConfig(
            keyframe_interval=args.keyframe_interval,
            cheap_mode=args.cheap_mode,
            staleness_max=args.staleness_max,
            frame_deadline_ms=args.frame_deadline_ms,
            session_ttl_s=args.session_ttl_s,
            reorder_window=args.reorder_window)
    server = make_server(pipeline, host=args.host, port=args.port,
                         colormap=get_colormap(cfg),
                         replica_id=args.replica_id,
                         artifact_version=version,
                         stream_config=stream_config)
    host, port = server.server_address[:2]
    if args.port_file:
        # --port 0 binds an ephemeral port; a fleet manager discovers it
        # here (write-then-rename so a concurrent reader never sees a
        # half-written file)
        tmp = args.port_file + '.tmp'
        with open(tmp, 'w') as f:
            f.write(f'{port}\n')
        os.replace(tmp, args.port_file)
    rid = f' | replica {args.replica_id}' if args.replica_id else ''
    if version:
        rid += f' | version {version}'
    extra = ' /session /frame' if stream_config is not None else ''
    print(f'segserve: {cfg.model} on http://{host}:{port}{rid} | buckets '
          f'{args.buckets} x batch {engine.batch} | POST /predict{extra} '
          f'/drain /debug/profile?ms=, GET /healthz /stats /metrics',
          flush=True)
    # SIGTERM == graceful drain (ROADMAP item 5): a fleet manager's (or
    # kubelet's) TERM stops admission (/predict answers 503), in-flight
    # requests run to completion, then the drain waiter stops the accept
    # loop — serve_forever returns, the finally flushes run_end into the
    # sink, and the process exits 0 with zero dropped work
    signal.signal(signal.SIGTERM,
                  lambda *_: server.begin_drain(exit_after=True))
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        pipeline.close()
        if sink is not None:
            sink.emit({'event': 'run_end'})
            sink.close()
            if obs.get_sink() is sink:
                obs.set_sink(None)
    return 0


def cmd_bench(args) -> int:
    sink = None
    if args.obs_dir:
        sink = obs.init_run(args.obs_dir, meta={
            'serve': True, 'model': args.model, 'buckets': args.buckets,
            'batch': args.batch, 'rps_target': args.rps})
        obs.set_sink(sink)
    targets = list(args.urls or [])
    if args.http:
        targets.append(args.http)
    if targets:
        # external server(s): pure urllib client — no local engine and no
        # model/config machinery; the server's buckets do the fitting.
        # Several --url targets round-robin client-side (replica list);
        # one target is a single replica or a segfleet router.
        buckets = parse_buckets(args.buckets)
        images = synth_images(buckets, seed=args.seed)
        payloads = [encode_png(im) for im in images]
        report = bench_http(targets, payloads, args.requests, args.rps,
                            seed=args.seed)
        try:
            if args.report_json:
                with open(args.report_json, 'w') as f:
                    json.dump(report, f, indent=2)
            print(json.dumps(report, indent=2) if args.json
                  else format_report(report), flush=True)
            if args.check:
                problems = check_report(
                    report, args.p95_ms,
                    max_replica_skew=args.max_replica_skew,
                    expect_replicas=args.expect_replicas)
                if problems:
                    print('segserve check FAILED: ' + '; '.join(problems),
                          file=sys.stderr)
                    return 1
            return 0
        finally:
            if sink is not None:
                sink.emit({'event': 'run_end'})
                sink.close()
                if obs.get_sink() is sink:
                    obs.set_sink(None)
    cfg = _build_config(args)
    engine = _build_engine(args, cfg)
    buckets = engine.buckets
    images = synth_images(buckets, seed=args.seed)
    try:
        if args.via_http:
            pipeline = _build_pipeline(args, cfg, engine)
            server = make_server(pipeline, host='127.0.0.1', port=0,
                                 colormap=get_colormap(cfg))
            port = server.server_address[1]
            t = threading.Thread(target=server.serve_forever, daemon=True)
            t.start()
            try:
                payloads = [encode_png(im) for im in images]
                report = bench_http(f'http://127.0.0.1:{port}', payloads,
                                    args.requests, args.rps,
                                    seed=args.seed)
            finally:
                server.shutdown()
                pipeline.close()
            report['engine'] = engine.stats()
            report['batcher'] = pipeline.batcher.stats()
        else:
            with _build_pipeline(args, cfg, engine) as pipeline:
                report = bench_pipeline(pipeline, images, args.requests,
                                        args.rps, seed=args.seed,
                                        deadline_ms=args.deadline_ms)
        if args.baseline:
            base_engine = ServeEngine.from_config(
                cfg, buckets, 1, ckpt_path=args.ckpt,
                name='serve_baseline')
            report['baseline'] = bench_sequential(
                base_engine, images, min(args.requests,
                                         args.baseline_requests))
        if args.report_json:
            with open(args.report_json, 'w') as f:
                json.dump(report, f, indent=2)
        print(json.dumps(report, indent=2) if args.json
              else format_report(report), flush=True)
        if args.check:
            problems = check_report(report, args.p95_ms,
                                    expect_buckets=len(buckets))
            if problems:
                print('segserve check FAILED: ' + '; '.join(problems),
                      file=sys.stderr)
                return 1
            print(f'segserve check OK: {report["ok"]}/{report["requests"]}'
                  f' ok, 0 drops/rejects, p95 '
                  f'{report["e2e_p95_ms"]:.1f} ms <= {args.p95_ms} ms, '
                  f'{len(buckets)} executables, 0 retraces')
        return 0
    finally:
        if sink is not None:
            sink.emit({'event': 'run_end'})
            sink.close()
            if obs.get_sink() is sink:
                obs.set_sink(None)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog='segserve', description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest='cmd', required=True)

    sp = sub.add_parser('serve', help='run the HTTP serving front-end')
    _add_engine_args(sp)
    sp.add_argument('--host', default='0.0.0.0')
    sp.add_argument('--port', type=int, default=8080,
                    help='0 binds an ephemeral port (printed, and '
                         'written to --port-file) — what the segfleet '
                         'replica manager spawns with')
    sp.add_argument('--port-file', default=None, metavar='PATH',
                    help='write the bound port here once listening '
                         '(atomic rename; fleet/CI port discovery)')
    sp.add_argument('--replica-id', default=None,
                    help='identity stamped into every response as '
                         'X-Replica-Id (per-replica attribution)')
    sp.add_argument('--obs-dir', default=None,
                    help='stream segscope ingress/request/batch events '
                         'here (tail with `segscope.py live`)')
    sp.add_argument('--stream', action='store_true',
                    help='mount the segstream video session plane '
                         '(POST /session, /frame — tools/segstream.py)')
    sp.add_argument('--keyframe-interval', type=int, default=8,
                    help='full network pass every K frames per session '
                         '(1 = keyframe every frame)')
    sp.add_argument('--cheap-mode', default='reuse',
                    choices=('reuse', 'warp', 'light'),
                    help='between keyframes: reuse the last mask, warp '
                         'it by estimated motion, or run a half-res '
                         'light pass')
    sp.add_argument('--staleness-max', type=float, default=0.25,
                    help='thumbnail mean-abs-diff vs the keyframe that '
                         'forces an early keyframe (warp/light modes)')
    sp.add_argument('--frame-deadline-ms', type=float, default=1000.0,
                    help='default per-frame deadline; late frames are '
                         'dropped (504), never served stale')
    sp.add_argument('--session-ttl-s', type=float, default=120.0,
                    help='idle sessions are swept after this long')
    sp.add_argument('--reorder-window', type=int, default=8,
                    help='max sequence-number gap buffered for '
                         'out-of-order frames before skipping ahead')

    bp = sub.add_parser('bench', help='open-loop Poisson load test')
    _add_engine_args(bp)
    bp.add_argument('--requests', type=int, default=256)
    bp.add_argument('--rps', type=float, default=50.0,
                    help='target arrival rate (open loop)')
    bp.add_argument('--seed', type=int, default=0)
    bp.add_argument('--http', default=None,
                    help='drive an already-running server at this URL')
    bp.add_argument('--url', action='append', dest='urls', default=None,
                    metavar='URL',
                    help='repeatable: drive several already-running '
                         'replicas round-robin (or point once at a '
                         'segfleet router); implies HTTP mode')
    bp.add_argument('--max-replica-skew', type=float, default=None,
                    help='--check also gates the per-replica balance '
                         '(report replica_skew <= this)')
    bp.add_argument('--expect-replicas', type=int, default=None,
                    help='--check also gates how many distinct '
                         'X-Replica-Id values served traffic')
    bp.add_argument('--via-http', action='store_true',
                    help='start a localhost server in-process and drive '
                         'it over real HTTP')
    bp.add_argument('--baseline', action='store_true',
                    help='also run the closed-loop sequential bs1 '
                         'baseline and report the throughput ratio')
    bp.add_argument('--baseline-requests', type=int, default=64)
    bp.add_argument('--obs-dir', default=None,
                    help='write segscope request/batch events here')
    bp.add_argument('--json', action='store_true')
    bp.add_argument('--report-json', default=None, metavar='PATH',
                    help='also write the report dict to this file '
                         '(CI reconciliation against a /metrics scrape)')
    bp.add_argument('--check', action='store_true',
                    help='CI gate (see module docstring)')
    bp.add_argument('--p95-ms', type=float, default=1000.0,
                    help='--check e2e p95 threshold')
    args = ap.parse_args(argv)
    return cmd_serve(args) if args.cmd == 'serve' else cmd_bench(args)


if __name__ == '__main__':
    sys.exit(main())
