#!/usr/bin/env python
"""segship — versioned artifact registry + canary/shadow rollout CLI.

Usage:
    # bake one model into a content-hashed ArtifactBundle and publish it
    python tools/segship.py bake --registry /var/segship \
        --model fastscnn --num_class 19 --buckets 512x1024,256x512 \
        --batch 8 --ckpt save/best.ckpt --channel stable

    # segquant: the same bake, post-training-quantized to int8 — smaller
    # StableHLO/exe members, a quant/QUANT.json calibration record, and
    # a bake-time mIoU-drop gate (the bake refuses past --quant-max-drop)
    python tools/segship.py bake --registry /var/segship \
        --model fastscnn --buckets 512x1024 --batch 8 \
        --quant int8 --quant-samples 8 --quant-max-drop 0.05 \
        --channel canary

    # registry contents: versions, sizes, channel pointers
    python tools/segship.py list --registry /var/segship [--model M]

    # re-hash every member of a published bundle (deploy gate)
    python tools/segship.py verify --registry /var/segship \
        --model fastscnn --ref @stable

    # point a channel at a version (atomic tmp+rename pointer flip)
    python tools/segship.py set-channel --registry /var/segship \
        --model fastscnn --channel canary --ref 0a1b2c

    # the rollout e2e (CI + BENCHMARKS.md "Canary rollout methodology"):
    # spawn the @stable fleet, shadow-mirror a sample of live traffic to
    # the candidate (outputs compared bit-for-bit, users only ever see
    # stable), then canary it at --weight with the RolloutController
    # watching per-version p99/errors/disagreement — auto-rollback on
    # regression, golden-replay-gated promote on clean
    python tools/segship.py rollout --registry /var/segship \
        --model fastscnn --canary @canary --weight 0.2 \
        --shadow-sample 0.3 --requests 200 --rps 40 \
        --expect promote --check

    # quantized rollout: an int8 candidate legitimately flips boundary
    # pixels, so the compare gate is an explicit argmax-agreement
    # tolerance instead of byte-equality, and --keep-shadow keeps live
    # mirrors running through the canary phase so the controller can
    # roll back on a sinking mean agreement (--min-agree-frac)
    python tools/segship.py rollout --registry /var/segship \
        --model fastscnn --canary @canary --agree-tol 0.9 \
        --min-agree-frac 0.9 --keep-shadow --expect promote --check

Replicas are real `tools/segserve.py serve --bundle` subprocesses: the
bundle manifest fixes buckets/batch/dtype, the baked executables
deserialize through the bundle's own exe/ cache, and every response
carries X-Artifact-Version. Rollout transitions land as `rollout` events
in the segscope sink (--obs-dir), next to the `fleet` lifecycle events
they cause.

Exit codes: 0 ok, 1 --check/verify failed, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rtseg_tpu import obs                                      # noqa: E402
from rtseg_tpu.fleet import (FleetManager, ReplicaGroup,       # noqa: E402
                             TrafficSplit, make_router)
from rtseg_tpu.registry import (Registry, RolloutController,   # noqa: E402
                                RolloutPolicy, bake_model)
from rtseg_tpu.registry.bundle import _f32_payloads            # noqa: E402
from rtseg_tpu.serve import (bench_http, check_report,         # noqa: E402
                             format_report, parse_buckets)

_SEGSERVE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         'segserve.py')


# -------------------------------------------------------------------- bake
def cmd_bake(args) -> int:
    reg = Registry(args.registry)
    staging = reg.staging_dir(args.model)
    t0 = time.perf_counter()
    manifest = bake_model(
        staging, args.model, args.num_class,
        parse_buckets(args.buckets), args.batch,
        compute_dtype=args.compute_dtype, ckpt_path=args.ckpt,
        golden=args.golden, seed=args.seed,
        perturb=args.perturb, perturb_seed=args.perturb_seed,
        miou=args.miou,
        quant=args.quant, quant_samples=args.quant_samples,
        quant_seed=args.quant_seed, quant_max_drop=args.quant_max_drop,
        quant_activations=args.quant_activations,
        quant_corrupt=args.quant_corrupt,
        quant_corrupt_seed=args.quant_corrupt_seed,
        calib_cache=args.calib_cache)
    version = reg.publish(args.model, staging)
    dur = time.perf_counter() - t0
    members = manifest['members']
    total = sum(int(m['bytes']) for m in members.values())
    line = (f'segship bake — {args.model} -> version {version} | '
            f'{len(members)} members, {total / 2**20:.1f} MiB, '
            f'{manifest["meta"]["buckets"]} x batch '
            f'{manifest["meta"]["batch"]} | '
            f'{manifest["meta"]["precision"]} | {dur:.1f} s')
    if args.perturb:
        line += f' | perturb {args.perturb}@{args.perturb_seed}'
    q = manifest['meta'].get('quant')
    if q:
        line += (f' | agreement {q["agreement_frac"]:.4f}, mIoU drop '
                 f'{q["miou_drop"]:.4f} <= {q["max_drop"]} '
                 f'({q["calib_source"]})')
        if q.get('corrupt'):
            line += f' | CORRUPTED scales {q["corrupt"]}'
    print(line, flush=True)
    if args.channel:
        reg.set_channel(args.model, args.channel, version)
        print(f'  channel {args.channel} -> {version}', flush=True)
    if args.json:
        print(json.dumps({'version': version,
                          'meta': manifest['meta']}, indent=2))
    return 0


# -------------------------------------------------------------------- list
def cmd_list(args) -> int:
    reg = Registry(args.registry)
    models = [args.model] if args.model else reg.models()
    out = {m: reg.describe(m) for m in models}
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    for m, d in out.items():
        chans = {c: p.get('version') for c, p in d['channels'].items()}
        print(f'segship — {m} | channels {chans or "{}"}')
        for v, info in d['versions'].items():
            tags = ''.join(f' @{c}' for c, pv in chans.items() if pv == v)
            print(f'  {v}{tags}: {info.get("members")} members '
                  f'{info.get("bytes", 0) / 2**20:.1f} MiB | buckets '
                  f'{info.get("buckets")} batch {info.get("batch")} | '
                  f'{info.get("precision")}'
                  + (f' | perturb {info["perturb"]}'
                     if info.get('perturb') else ''))
            bb = info.get('bucket_bytes') or {}
            if bb:
                print('      hlo: ' + ' '.join(
                    f'{b}={n / 2**10:.0f}KiB'
                    for b, n in sorted(bb.items())))
            q = info.get('quant')
            if q:
                print(f'      quant: calib {q.get("calib_hash", "")[:12]}'
                      f' ({q.get("calib_source")}) | agreement '
                      f'{q.get("agreement_frac"):.4f} | mIoU drop '
                      f'{q.get("miou_drop"):.4f} <= {q.get("max_drop")}'
                      + (f' | CORRUPTED {q["corrupt"]}'
                         if q.get('corrupt') else ''))
    return 0


# ------------------------------------------------------------------ verify
def cmd_verify(args) -> int:
    reg = Registry(args.registry)
    problems = reg.verify(args.model, args.ref)
    version = None
    try:
        version = reg.resolve(args.model, args.ref)
    except Exception:   # noqa: BLE001 — the problem list already says
        pass
    if problems:
        print(f'segship verify FAILED — {args.model} '
              f'{args.ref or "@stable"} ({version}): '
              + '; '.join(problems), file=sys.stderr, flush=True)
        return 1
    provenance = ''
    if version is not None:
        try:
            from rtseg_tpu.registry.bundle import load_manifest
            meta = load_manifest(
                reg.version_dir(args.model, version)).get('meta', {})
            provenance = f' | {meta.get("precision")}'
            q = meta.get('quant')
            if q:
                provenance += (f', calib {q.get("calib_hash", "")[:12]}, '
                               f'agreement {q.get("agreement_frac"):.4f} '
                               f'(gate drop <= {q.get("max_drop")})')
        except Exception:   # noqa: BLE001 — provenance is decoration;
            pass            # the verify verdict above is the contract
    print(f'segship verify OK — {args.model} {args.ref or "@stable"} '
          f'({version}): every member re-hashed clean{provenance}',
          flush=True)
    return 0


def cmd_set_channel(args) -> int:
    reg = Registry(args.registry)
    version = reg.resolve(args.model, args.ref)
    pointer = reg.set_channel(args.model, args.channel, version)
    print(f'segship: {args.model} channel {args.channel} -> {version} '
          f'(was {pointer.get("previous")})', flush=True)
    return 0


# ----------------------------------------------------------------- rollout
def _bundle_spawn_cmd(bundle_dir: str, args, max_wait_ms: float):
    def cmd(rid: str, port_file: str):
        return [sys.executable, _SEGSERVE, 'serve',
                '--bundle', bundle_dir,
                '--host', '127.0.0.1', '--port', '0',
                '--port-file', port_file,
                '--replica-id', rid,
                '--max-wait-ms', str(max_wait_ms),
                '--max-queue', str(args.max_queue),
                '--workers', str(args.workers)]
    return cmd


def _scrape_ok(replicas) -> int:
    from rtseg_tpu.obs.live import scrape_counter_sum
    return scrape_counter_sum([r.url for r in replicas],
                              'serve_requests_total', status='ok')


def _ok_by_version(router, group: str) -> dict:
    return {v: int(st.get('ok', 0))
            for v, st in router.version_stats(group).items()
            if v != 'shadow'}


def cmd_rollout(args) -> int:
    obs_dir = args.obs_dir or '/tmp/segship_rollout/segscope'
    sink = obs.init_run(obs_dir, meta={
        'segship': True, 'model': args.model, 'weight': args.weight,
        'shadow_sample': args.shadow_sample})
    obs.set_sink(sink)
    reg = Registry(args.registry)
    stable_v = reg.resolve(args.model, args.stable)
    canary_v = reg.resolve(args.model, args.canary)
    if stable_v == canary_v:
        print(f'segship: stable and canary both resolve to {stable_v}; '
              f'nothing to roll out', file=sys.stderr)
        return 2
    stable_dir = reg.version_dir(args.model, stable_v)
    canary_dir = reg.version_dir(args.model, canary_v)
    problems = []
    for tag, ref in (('stable', stable_v), ('canary', canary_v)):
        bad = reg.verify(args.model, ref)
        if bad:
            # never roll out (or keep serving) a corrupt bundle
            print(f'segship: {tag} bundle failed verify: '
                  + '; '.join(bad), file=sys.stderr)
            return 1
    payloads = _f32_payloads(stable_dir)
    if not payloads:
        print('segship: stable bundle has no golden payloads to drive '
              'traffic with', file=sys.stderr)
        return 2

    stable_channel_before = reg.channel(args.model, 'stable')
    group = args.model
    stable_rg = ReplicaGroup(
        group, _bundle_spawn_cmd(stable_dir, args, args.max_wait_ms),
        min_replicas=args.replicas, max_replicas=max(args.replicas, 4))
    canary_rg = ReplicaGroup(
        f'{group}-canary',
        _bundle_spawn_cmd(canary_dir, args, args.canary_max_wait_ms
                          if args.canary_max_wait_ms is not None
                          else args.max_wait_ms),
        min_replicas=args.canary_replicas,
        max_replicas=max(args.canary_replicas, 4))
    manager = FleetManager([stable_rg], run_dir=args.run_dir,
                           drain_grace_s=args.drain_grace_s)
    split = TrafficSplit(stable_rg, stable_version=stable_v)
    router = None
    controller = None
    report = {'model': args.model, 'stable': stable_v,
              'canary': canary_v, 'weight': args.weight}
    t_start = time.perf_counter()
    try:
        manager.start()
        replicas = manager.wait_ready(group, args.replicas,
                                      timeout_s=args.ready_timeout_s)
        manager.add_group(canary_rg)
        canaries = manager.wait_ready(canary_rg.name,
                                      args.canary_replicas,
                                      timeout_s=args.ready_timeout_s)
        report['spinup'] = {
            **{r.replica_id: round(r.ready_s, 2) for r in replicas},
            **{r.replica_id: round(r.ready_s, 2) for r in canaries}}
        router = make_router({group: split}, host='127.0.0.1',
                             port=args.port,
                             max_outstanding=args.max_outstanding)
        threading.Thread(target=router.serve_forever,
                         daemon=True).start()
        url = f'http://127.0.0.1:{router.server_address[1]}'
        print(f'segship rollout — {args.model}: stable {stable_v} '
              f'({len(replicas)} replicas) vs canary {canary_v} '
              f'({len(canaries)}) | router {url} | spin-up '
              + ' '.join(f'{k}={v}s'
                         for k, v in report['spinup'].items()),
              flush=True)

        # ---- phase S: shadow — mirror a sample of stable traffic to
        # the candidate; users only ever get stable answers
        if args.shadow_sample > 0:
            router.configure_shadow(group, canary_rg, canary_v,
                                    args.shadow_sample,
                                    agree_tol=args.agree_tol)
            before_can = _scrape_ok(canaries)
            shadow_bench = bench_http(url, payloads,
                                      args.shadow_requests, args.rps,
                                      seed=args.seed, query='raw=1')
            # mirrors are daemon threads (and the canary batcher may
            # hold them a full coalescing window): wait for QUIESCENCE —
            # two consecutive polls where the router's compare tally and
            # the canary replicas' serve count agree and stopped moving
            deadline = time.monotonic() + 60
            counts = {}
            last = (-1, -1)
            while time.monotonic() < deadline:
                counts = dict(router.version_stats(group)
                              .get('shadow', {}))
                n = sum(int(counts.get(k, 0))
                        for k in ('agree', 'disagree', 'error'))
                delta = _scrape_ok(canaries) - before_can
                if n and n == delta and (n, delta) == last:
                    break
                last = (n, delta)
                time.sleep(0.25)
            if not args.keep_shadow:
                router.groups[group].clear_shadow()
            mirrors = sum(int(counts.get(k, 0))
                          for k in ('agree', 'disagree', 'error'))
            report['shadow'] = {
                'requests': shadow_bench['requests'],
                'ok': shadow_bench['ok'],
                'errors': shadow_bench['errors'],
                'mirrors': mirrors,
                'canary_serve_delta': _scrape_ok(canaries) - before_can,
                **{k: int(v) for k, v in counts.items()
                   if k in ('agree', 'disagree', 'error')},
                'agree_frac': counts.get('agree_frac'),
            }
            print(f'  shadow         : {mirrors} mirrored of '
                  f'{shadow_bench["ok"]} ok | agree '
                  f'{counts.get("agree", 0)} (tol {args.agree_tol}) | '
                  f'disagree {counts.get("disagree", 0)} | mean raw '
                  f'agreement {counts.get("agree_frac")}', flush=True)
            if shadow_bench['errors']:
                problems.append(f'shadow phase: '
                                f'{shadow_bench["errors"]} client '
                                f'errors (want 0)')
            if mirrors == 0:
                problems.append('shadow phase mirrored nothing')
            if mirrors != report['shadow']['canary_serve_delta']:
                problems.append(
                    f'shadow reconciliation: {mirrors} mirrors != '
                    f'{report["shadow"]["canary_serve_delta"]} canary '
                    f'serve oks')
            if args.expect_shadow == 'disagree' \
                    and not counts.get('disagree'):
                problems.append('expected shadow disagreement, saw none')
            if args.expect_shadow == 'agree' \
                    and counts.get('disagree'):
                problems.append(f'expected bit-agreement, '
                                f'{counts["disagree"]} mirrors '
                                f'disagreed')

        # ---- phase C: canary — weighted sticky split + controller
        router.configure_canary(group, canary_rg, canary_v, args.weight)
        policy = RolloutPolicy(
            p99_regress_frac=args.p99_regress_frac,
            p99_floor_ms=args.p99_floor_ms,
            max_disagree_frac=args.max_disagree,
            min_agree_frac=args.min_agree_frac,
            min_canary_ok=args.min_canary_ok,
            min_stable_ok=args.min_stable_ok,
            breach_consecutive=args.breach_consecutive,
            clean_consecutive=args.clean_consecutive)
        controller = RolloutController(
            router, manager, reg, group, canary_v, canary_rg.name,
            bundle_dir=canary_dir, old_stable_group=group,
            policy=policy, poll_s=args.poll_s)
        before_rtr = _ok_by_version(router, group)
        before_stable = _scrape_ok(replicas)
        before_canary = _scrape_ok(canaries)
        # the rollout's starting line is NOW (canary arm live): the
        # baseline snapshot + canary_start event fire here even when
        # the polling thread starts after the bench
        controller.prime()
        live = args.expect == 'rollback'
        if live:
            # the controller watches the bench as it runs: a seeded
            # regression must roll back MID-traffic with zero
            # client-visible errors (the canary hash slice falls back
            # to stable the moment the arm clears)
            controller.start()
        # --keep-shadow drives raw traffic so the live mirrors keep
        # comparing int8 masks per-pixel (the agree_frac the controller
        # gates on); version attribution rides in headers either way
        bench = bench_http(url, payloads, args.requests, args.rps,
                           seed=args.seed + 1,
                           query='raw=1' if args.keep_shadow else '')
        report['canary_bench'] = bench
        print(format_report(bench), flush=True)
        after_rtr = _ok_by_version(router, group)
        rtr_delta = {v: after_rtr.get(v, 0) - before_rtr.get(v, 0)
                     for v in after_rtr}
        recon = {'loadgen_per_version': bench.get('per_version'),
                 'router_delta': rtr_delta}
        if not live:
            # replica-side leg BEFORE the controller acts (a promote
            # drains the old stable group; a golden replay adds direct
            # canary traffic) — after it, only bookkept deltas exist
            recon['stable_serve_delta'] = \
                _scrape_ok(replicas) - before_stable
            recon['canary_serve_delta'] = \
                _scrape_ok(canaries) - before_canary
        report['reconciliation'] = recon
        print(f'  reconciliation : loadgen {recon["loadgen_per_version"]}'
              f' == router {rtr_delta}', flush=True)
        for v, n in rtr_delta.items():
            if n != (bench.get('per_version') or {}).get(v, 0):
                problems.append(
                    f'per-version reconciliation mismatch for {v}: '
                    f'router {n} != loadgen '
                    f'{(bench.get("per_version") or {}).get(v, 0)}')
        if sum(rtr_delta.values()) != bench['ok']:
            problems.append(f'router ok sum {sum(rtr_delta.values())} '
                            f'!= loadgen ok {bench["ok"]}')
        if not live:
            if recon['stable_serve_delta'] != rtr_delta.get(stable_v, 0):
                problems.append(
                    f'stable replicas served '
                    f'{recon["stable_serve_delta"]}, router says '
                    f'{rtr_delta.get(stable_v, 0)}')
            # under --keep-shadow the canary replicas also serve the
            # live mirrors (which the router books as shadow results,
            # not fleet_requests), so the exact-equality leg holds only
            # without a live shadow arm; the mirror side reconciles in
            # phase S instead
            if not args.keep_shadow and \
                    recon['canary_serve_delta'] != rtr_delta.get(
                        canary_v, 0):
                problems.append(
                    f'canary replicas served '
                    f'{recon["canary_serve_delta"]}, router says '
                    f'{rtr_delta.get(canary_v, 0)}')
        problems += check_report(
            bench, args.p95_ms,
            canary_version=canary_v if not live else None,
            canary_weight=args.weight if not live else None,
            canary_weight_tol=args.weight_tol)
        if not live:
            controller.start()
        outcome = controller.wait(timeout_s=args.decide_timeout_s)
        controller.stop()
        action, reason = outcome if outcome else ('none', 'undecided')
        report['outcome'] = {'action': action, 'reason': reason}
        print(f'  outcome        : {action} — {reason}', flush=True)
        if args.expect != 'none' and action != args.expect:
            problems.append(f'expected {args.expect}, controller '
                            f'decided {action} ({reason})')
        now_stable = reg.channel(args.model, 'stable')
        report['stable_channel_after'] = now_stable
        if action == 'promote' and now_stable != canary_v:
            problems.append(f'promote did not flip the stable channel '
                            f'(still {now_stable})')
        if action == 'rollback' and now_stable != stable_channel_before:
            problems.append(f'rollback must not move the stable '
                            f'channel ({stable_channel_before} -> '
                            f'{now_stable})')

        # ---- phase P: post-action traffic — whatever the controller
        # decided, clients must see exactly one version and zero errors
        expected_v = canary_v if action == 'promote' else stable_v
        report['post_expected_version'] = expected_v
        post = bench_http(url, payloads, args.post_requests, args.rps,
                          seed=args.seed + 2)
        report['post_bench'] = post
        print(f'  post-{action:<9}: {post["ok"]}/{post["requests"]} ok | '
              f'{post["errors"]} errors | versions '
              f'{post.get("per_version")}', flush=True)
        if post['errors'] or post['ok'] != post['requests']:
            problems.append(
                f'post-{action} traffic lost requests: '
                f'{post["ok"]}/{post["requests"]} ok, '
                f'{post["errors"]} errors')
        if set(post.get('per_version') or {}) != {expected_v}:
            problems.append(
                f'post-{action} traffic saw versions '
                f'{post.get("per_version")}, expected only '
                f'{expected_v}')
    finally:
        if controller is not None:
            controller.stop()
        if router is not None:
            router.shutdown()
        manager.stop(drain=False)
        sink.emit({'event': 'run_end'})
        sink.close()
        if obs.get_sink() is sink:
            obs.set_sink(None)

    events = []
    for name in sorted(os.listdir(obs_dir)):
        if name.startswith('events-') and name.endswith('.jsonl'):
            with open(os.path.join(obs_dir, name)) as f:
                events += [json.loads(line) for line in f
                           if line.strip()]
    actions = [e['action'] for e in events
               if e.get('event') == 'rollout']
    report['rollout_events'] = {a: actions.count(a)
                                for a in sorted(set(actions))}
    report['wall_s'] = round(time.perf_counter() - t_start, 1)
    print(f'  rollout events : {report["rollout_events"]} '
          f'(sink {obs_dir})', flush=True)
    if 'canary_start' not in actions:
        problems.append('no canary_start rollout event reached the sink')
    if args.expect != 'none' and args.expect not in actions:
        problems.append(f'no {args.expect} rollout event reached the '
                        f'sink')
    if args.report_json:
        os.makedirs(os.path.dirname(os.path.abspath(args.report_json)),
                    exist_ok=True)
        with open(args.report_json, 'w') as f:
            json.dump(report, f, indent=2)
    if args.check:
        if problems:
            print('segship check FAILED: ' + '; '.join(problems),
                  file=sys.stderr, flush=True)
            return 1
        print(f'segship check OK: {report["outcome"]["action"]} of '
              f'{canary_v} over {stable_v} | canary bench '
              f'{report["canary_bench"]["ok"]}/'
              f'{report["canary_bench"]["requests"]} ok, 0 errors | '
              f'exact per-version reconciliation | post-action '
              f'{report["post_bench"]["ok"]}/'
              f'{report["post_bench"]["requests"]} ok on '
              f'{report.get("post_expected_version")} | '
              f'{report["wall_s"]}s', flush=True)
    return 0


# --------------------------------------------------------------------- main
def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog='segship', description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest='cmd', required=True)

    bp = sub.add_parser('bake', help='build + publish an ArtifactBundle')
    bp.add_argument('--registry', required=True)
    bp.add_argument('--model', default='fastscnn')
    bp.add_argument('--num_class', type=int, default=19)
    bp.add_argument('--compute_dtype', default=None)
    bp.add_argument('--buckets', default='512x1024')
    bp.add_argument('--batch', type=int, default=8)
    bp.add_argument('--ckpt', default=None)
    bp.add_argument('--golden', type=int, default=4,
                    help='golden input/output pairs recorded at bake')
    bp.add_argument('--seed', type=int, default=0)
    bp.add_argument('--perturb', type=float, default=0.0,
                    help='seeded gaussian weight noise — the rollout-'
                         'drill knob (bakes a deliberately-different '
                         'version the shadow compare must catch)')
    bp.add_argument('--perturb-seed', type=int, default=0)
    bp.add_argument('--miou', type=float, default=None,
                    help='held-out mIoU measured by the baker (recorded '
                         'in quality.json)')
    bp.add_argument('--quant', default=None, choices=('int8',),
                    help='segquant: post-training quantize the weights '
                         '(per-channel symmetric int8) before export; '
                         'the bundle ships int8 StableHLO + the '
                         'quant/QUANT.json calibration record')
    bp.add_argument('--quant-samples', type=int, default=8,
                    help='calibration sample count (seeded selection)')
    bp.add_argument('--quant-seed', type=int, default=0)
    bp.add_argument('--quant-max-drop', type=float, default=0.05,
                    help='the bake REFUSES when the calibrated mIoU '
                         'drop exceeds this (vs ground truth with '
                         '--calib-cache, vs the f32 forward otherwise)')
    bp.add_argument('--quant-activations', action='store_true',
                    help='also calibrate per-tensor activation scales '
                         'and quantize the input boundary (QDQ)')
    bp.add_argument('--quant-corrupt', type=float, default=0.0,
                    help='seeded noise on the scale vectors AFTER '
                         'calibration — the quantized rollout drill '
                         '(bypasses the max-drop gate so the bad '
                         'bundle ships to the shadow/rollout planes)')
    bp.add_argument('--quant-corrupt-seed', type=int, default=0)
    bp.add_argument('--calib-cache', default=None,
                    help='segpipe PackedCache dir to calibrate on (real '
                         'samples + ground-truth mIoU; default: seeded '
                         'synthetic through the serving preprocess)')
    bp.add_argument('--channel', default=None,
                    help='also point this channel at the new version')
    bp.add_argument('--json', action='store_true')

    lp = sub.add_parser('list', help='versions + channel pointers')
    lp.add_argument('--registry', required=True)
    lp.add_argument('--model', default=None)
    lp.add_argument('--json', action='store_true')

    vp = sub.add_parser('verify', help='re-hash a published bundle')
    vp.add_argument('--registry', required=True)
    vp.add_argument('--model', required=True)
    vp.add_argument('--ref', default=None,
                    help='@channel or version prefix (default @stable)')

    cp = sub.add_parser('set-channel', help='atomic channel pointer flip')
    cp.add_argument('--registry', required=True)
    cp.add_argument('--model', required=True)
    cp.add_argument('--channel', required=True)
    cp.add_argument('--ref', required=True)

    rp = sub.add_parser('rollout',
                        help='shadow + canary a version against @stable')
    rp.add_argument('--registry', required=True)
    rp.add_argument('--model', default='fastscnn')
    rp.add_argument('--stable', default='@stable')
    rp.add_argument('--canary', default='@canary')
    rp.add_argument('--weight', type=float, default=0.2)
    rp.add_argument('--shadow-sample', type=float, default=0.3)
    rp.add_argument('--replicas', type=int, default=1)
    rp.add_argument('--canary-replicas', type=int, default=1)
    rp.add_argument('--requests', type=int, default=200)
    rp.add_argument('--shadow-requests', type=int, default=64)
    rp.add_argument('--post-requests', type=int, default=32)
    rp.add_argument('--rps', type=float, default=40.0)
    rp.add_argument('--seed', type=int, default=0)
    rp.add_argument('--max-wait-ms', type=float, default=10.0)
    rp.add_argument('--canary-max-wait-ms', type=float, default=None,
                    help='override the canary replicas\' batcher wait — '
                         'the seeded-regression knob for rollback drills'
                         ' (a big wait legitimately inflates canary p99)')
    rp.add_argument('--max-queue', type=int, default=128)
    rp.add_argument('--workers', type=int, default=2)
    rp.add_argument('--port', type=int, default=0)
    rp.add_argument('--max-outstanding', type=int, default=256)
    rp.add_argument('--run-dir', default=None)
    rp.add_argument('--ready-timeout-s', type=float, default=600.0)
    rp.add_argument('--drain-grace-s', type=float, default=60.0)
    rp.add_argument('--poll-s', type=float, default=0.5)
    rp.add_argument('--decide-timeout-s', type=float, default=120.0)
    rp.add_argument('--p99-regress-frac', type=float, default=0.5)
    rp.add_argument('--p99-floor-ms', type=float, default=50.0)
    rp.add_argument('--max-disagree', type=float, default=0.02)
    rp.add_argument('--agree-tol', type=float, default=1.0,
                    help='per-compare agreement fraction below which a '
                         'mirrored raw mask counts as disagree (1.0 = '
                         'byte-exact; an int8 canary states its argmax-'
                         'agreement tolerance here)')
    rp.add_argument('--min-agree-frac', type=float, default=0.0,
                    help='rollback when the windowed mean per-pixel '
                         'agreement sinks below this (0 disables; '
                         'needs --keep-shadow for live mirrors during '
                         'the canary phase)')
    rp.add_argument('--keep-shadow', action='store_true',
                    help='keep mirroring through the canary phase so '
                         'the controller sees a live agree_frac (drives '
                         'raw traffic; relaxes the canary replica-side '
                         'reconciliation leg)')
    rp.add_argument('--min-canary-ok', type=int, default=10)
    rp.add_argument('--min-stable-ok', type=int, default=10)
    rp.add_argument('--breach-consecutive', type=int, default=2)
    rp.add_argument('--clean-consecutive', type=int, default=3)
    rp.add_argument('--p95-ms', type=float, default=10000.0)
    rp.add_argument('--weight-tol', type=float, default=0.15)
    rp.add_argument('--expect', default='none',
                    choices=('promote', 'rollback', 'none'),
                    help='--check gates that the controller reached '
                         'this verdict')
    rp.add_argument('--expect-shadow', default='any',
                    choices=('agree', 'disagree', 'any'),
                    help='--check gates the shadow compare outcome')
    rp.add_argument('--obs-dir', default=None)
    rp.add_argument('--report-json', default=None, metavar='PATH')
    rp.add_argument('--check', action='store_true')

    args = ap.parse_args(argv)
    return {'bake': cmd_bake, 'list': cmd_list, 'verify': cmd_verify,
            'set-channel': cmd_set_channel,
            'rollout': cmd_rollout}[args.cmd](args)


if __name__ == '__main__':
    sys.exit(main())
