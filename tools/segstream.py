#!/usr/bin/env python
"""segstream — streaming video segmentation bench (rtseg_tpu/stream/).

Usage:
    # the streaming e2e gate (CI + BENCHMARKS.md "Video serving
    # methodology"): N replicas behind the affinity router, 4 video
    # sessions at a fixed fps, SIGKILL a replica mid-stream (affinity
    # re-homes its sessions with a forced keyframe: 0 client errors,
    # >= 1 session_migrate), exact router-vs-replica-vs-loadgen frame
    # reconciliation, 0 retraces, then a keyframe-every-frame reference
    # pass over the SAME payloads for the honest quality/throughput
    # trade table (mIoU delta + temporal consistency + speedup)
    python tools/segstream.py bench --replicas 2 --sessions 4 \
        --buckets 64x64 --batch 4 --fps 10 --frames 32 --check

Replicas are real `tools/segserve.py serve --stream` subprocesses; the
router is the segfleet front door with session-affinity routing
(rendezvous hash over ready replicas), so every phase exercises the
production code path end to end. Reports follow the segfleet/segship
house style: --json, --report-json PATH, --check gates.

Exit codes: 0 ok, 1 --check failed, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rtseg_tpu import obs                                      # noqa: E402
from rtseg_tpu.fleet import (FleetManager, ReplicaGroup,       # noqa: E402
                             get_policy, make_router)
from rtseg_tpu.obs.live import scrape_counter_sum              # noqa: E402
from rtseg_tpu.serve import (bench_video, check_video_report,  # noqa: E402
                             format_video_report,
                             make_video_payloads, parse_buckets)
from rtseg_tpu.stream import quality_delta                     # noqa: E402

_SEGSERVE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         'segserve.py')


# ------------------------------------------------------------------ plumbing
def make_spawn_cmd(args, obs_root=None):
    """argv builder handed to the ReplicaGroup: each replica is a real
    segserve process with the session plane mounted, warm through the
    shared compile cache."""
    def cmd(rid: str, port_file: str):
        argv = [sys.executable, _SEGSERVE, 'serve', '--stream',
                '--model', args.model,
                '--num_class', str(args.num_class),
                '--buckets', args.buckets,
                '--batch', str(args.batch),
                '--max-wait-ms', str(args.max_wait_ms),
                '--max-queue', str(args.max_queue),
                '--workers', str(args.workers),
                '--keyframe-interval', str(args.keyframe_interval),
                '--cheap-mode', args.cheap_mode,
                '--frame-deadline-ms', str(args.frame_deadline_ms),
                '--session-ttl-s', str(args.session_ttl_s),
                '--host', '127.0.0.1', '--port', '0',
                '--port-file', port_file,
                '--replica-id', rid]
        if args.compute_dtype:
            argv += ['--compute_dtype', args.compute_dtype]
        if args.compile_cache:
            argv += ['--compile-cache', args.compile_cache]
        if args.ckpt:
            argv += ['--ckpt', args.ckpt]
        if obs_root:
            argv += ['--obs-dir', os.path.join(obs_root,
                                               f'replica-{rid}')]
        return argv
    return cmd


def _frame_counts(router_url, replicas, group: str) -> dict:
    """The two counter legs of the frame reconciliation: the router's
    fleet_frames_total{ok} and the sum of replica-side
    stream_frames_total{ok} (frontend-incremented — cheap frames never
    reach the batcher, so serve_requests_total can't stand in)."""
    return {
        'router_ok': scrape_counter_sum(router_url, 'fleet_frames_total',
                                        group=group, status='ok'),
        'replica_ok': scrape_counter_sum([r.url for r in replicas],
                                         'stream_frames_total',
                                         status='ok'),
    }


def _replica_engine_stats(replicas) -> dict:
    import urllib.request
    out = {}
    for r in replicas:
        if not r.url:
            continue
        try:
            with urllib.request.urlopen(r.url + '/stats',
                                        timeout=10) as resp:
                stats = json.loads(resp.read())
        except OSError:
            continue
        eng = stats.get('engine') or {}
        out[r.replica_id] = {'retraces': eng.get('retraces'),
                             'executables': eng.get('executables')}
    return out


def _sink_events(obs_dir: str) -> list:
    events = []
    for name in sorted(os.listdir(obs_dir)):
        if name.startswith('events-') and name.endswith('.jsonl'):
            with open(os.path.join(obs_dir, name)) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        events.append(json.loads(line))
    return events


# -------------------------------------------------------------------- bench
def cmd_bench(args) -> int:
    obs_dir = args.obs_dir or '/tmp/segstream_bench/segscope'
    sink = obs.init_run(obs_dir, meta={
        'stream': True, 'bench': True, 'model': args.model,
        'buckets': args.buckets, 'batch': args.batch,
        'replicas': args.replicas, 'sessions': args.sessions,
        'keyframe_interval': args.keyframe_interval,
        'cheap_mode': args.cheap_mode})
    obs.set_sink(sink)
    group = ReplicaGroup('stream', make_spawn_cmd(args, obs_root=obs_dir),
                         min_replicas=1, max_replicas=args.replicas)
    manager = FleetManager([group], run_dir=args.run_dir,
                           drain_grace_s=args.drain_grace_s)
    buckets = parse_buckets(args.buckets)
    bucket = buckets[0]
    payloads = make_video_payloads(bucket, args.sessions, args.frames,
                                   seed=args.seed)
    problems = []
    report = {'buckets': args.buckets, 'batch': args.batch,
              'replicas': args.replicas, 'sessions': args.sessions,
              'frames': args.frames, 'fps': args.fps,
              'keyframe_interval': args.keyframe_interval,
              'cheap_mode': args.cheap_mode}
    router = None
    t_start = time.perf_counter()
    try:
        # ---- spin-up: first replica fills the shared compile cache,
        # the rest warm-start from it
        manager.start()
        manager.wait_ready('stream', 1, timeout_s=args.ready_timeout_s)
        if args.replicas > 1:
            manager.scale_to('stream', args.replicas,
                             reason='bench spin-up')
        replicas = manager.wait_ready('stream', args.replicas,
                                      timeout_s=args.ready_timeout_s)
        report['spinup'] = {r.replica_id: round(r.ready_s, 2)
                           for r in replicas}
        print(f'segstream bench — {args.replicas}x {args.model} '
              f'{args.buckets} batch {args.batch} | spin-up '
              + ' '.join(f'{k}={v}s'
                         for k, v in report['spinup'].items()),
              flush=True)
        router = make_router({'stream': group}, host='127.0.0.1',
                             port=args.port,
                             policy=get_policy(args.policy),
                             max_outstanding=args.max_outstanding)
        threading.Thread(target=router.serve_forever,
                         daemon=True).start()
        host, port = router.server_address[:2]
        url = f'http://{host}:{port}'
        print(f'  router         : {url} | session-affinity over '
              f'{args.replicas} replicas', flush=True)

        # ---- phase A: steady streaming — N sessions at fixed fps, no
        # faults. Gates: zero losses, keyframe ratio in band, EXACT
        # frame reconciliation (every ok the loadgen saw is one router
        # forward and one replica frontend answer — no slack)
        before = _frame_counts(url, replicas, 'stream')
        sched_masks: dict = {}
        steady = bench_video(
            url, payloads, args.fps, bucket,
            frame_deadline_ms=args.frame_deadline_ms,
            timeout_s=args.timeout_s, mask_store=sched_masks)
        report['steady'] = steady
        print(format_video_report(steady), flush=True)
        expect_ratio = 1.0 / args.keyframe_interval
        band = (args.keyframe_band_lo or 0.8 * expect_ratio,
                args.keyframe_band_hi or
                min(1.0, 1.6 * expect_ratio))
        report['keyframe_band'] = list(band)
        problems += check_video_report(
            steady, p99_ms=args.p99_ms, keyframe_band=band,
            max_dropped_late=args.max_dropped_late,
            expect_sessions=args.sessions)
        after = _frame_counts(url, replicas, 'stream')
        recon = {'loadgen_ok': steady['ok'],
                 'router_ok_delta': after['router_ok']
                 - before['router_ok'],
                 'replica_ok_delta': after['replica_ok']
                 - before['replica_ok']}
        report['reconciliation'] = recon
        if len(set(recon.values())) != 1:
            problems.append(f'frame reconciliation mismatch: {recon}')
        print(f'  reconciliation : loadgen {recon["loadgen_ok"]} == '
              f'router {recon["router_ok_delta"]} == replicas '
              f'{recon["replica_ok_delta"]}', flush=True)

        # ---- phase B: SIGKILL a replica mid-stream. Affinity re-homes
        # its sessions onto survivors with a forced keyframe; the gate
        # is zero client-visible errors and at least one migration.
        box = {}

        def _run_kill():
            box['r'] = bench_video(
                url, payloads, args.fps, bucket,
                frame_deadline_ms=args.frame_deadline_ms,
                timeout_s=args.timeout_s)

        t = threading.Thread(target=_run_kill)
        t.start()
        time.sleep((args.frames / args.fps) * 0.4)
        victim = replicas[-1]
        os.kill(victim.pid, signal.SIGKILL)
        t.join(timeout=600)
        kill = box.get('r')
        if kill is None:
            problems.append('kill phase did not complete')
            report['kill'] = None
        else:
            report['kill'] = kill
            print(f'  kill mid-stream: SIGKILL {victim.replica_id} at '
                  f'40% of the stream -> {kill["ok"]} ok | '
                  f'{kill["errors"]} errors | {kill["dropped_late"]} '
                  f'dropped-late | {kill["sessions_migrated"]} sessions '
                  f'migrated', flush=True)
            if kill['errors'] or kill['rejected']:
                problems.append(
                    f'kill phase saw client-visible failures: '
                    f'{kill["errors"]} errors, {kill["rejected"]} '
                    f'rejected (want 0)')
            if kill['sessions_migrated'] < 1:
                problems.append('no session migrated across the kill '
                                '(affinity re-home did not happen)')
            if kill['dropped_late'] > args.max_kill_dropped_late:
                problems.append(
                    f'{kill["dropped_late"]} dropped-late frames across '
                    f'the kill > {args.max_kill_dropped_late}')
        deadline = time.monotonic() + args.ready_timeout_s
        while victim.state != 'ready' and time.monotonic() < deadline:
            time.sleep(0.1)
        report['victim_restarted'] = victim.state == 'ready'
        if not report['victim_restarted']:
            problems.append('killed replica was not restarted in time')
        replicas = manager.wait_ready('stream', args.replicas,
                                      timeout_s=args.ready_timeout_s)

        # ---- phase C: the honest quality/throughput table — a
        # keyframe-every-frame reference pass over the SAME payloads
        # (per-session override keyframe_interval=1), then per-frame
        # mIoU of scheduled-vs-reference masks. Temporal consistency is
        # reported for both but never alone: a scheduler that reuses
        # masks is *by construction* more consistent, so the mIoU delta
        # is what keeps the claim honest.
        ref_masks: dict = {}
        reference = bench_video(
            url, payloads, args.fps, bucket, keyframe_interval=1,
            frame_deadline_ms=args.frame_deadline_ms,
            timeout_s=args.timeout_s, mask_store=ref_masks)
        report['reference'] = reference
        delta = quality_delta(sched_masks, ref_masks,
                              num_class=args.num_class)
        report['quality'] = {
            'frames_compared': delta['frames_compared'],
            'mean_miou': delta['mean_miou'],
            'min_miou': delta['min_miou'],
            'consistency_scheduled': steady.get('consistency'),
            'keyframe_ratio_scheduled': steady.get('keyframe_ratio'),
            'keyframe_ratio_reference': reference.get('keyframe_ratio'),
        }
        p50_s, p50_r = steady.get('frame_p50_ms'), \
            reference.get('frame_p50_ms')
        # same offered load both passes (open loop): the ratio includes
        # any queueing the K=1 pass builds — that IS the point, a
        # keyframe-every-frame fleet saturating at this fps is the cost
        # the scheduler avoids
        speedup = (round(p50_r / p50_s, 2)
                   if p50_s and p50_r else None)
        report['quality']['frame_p50_speedup'] = speedup
        print(f'  reference      : keyframe-every-frame over the same '
              f'payloads at the same fps -> p50 {p50_r:.1f} ms '
              f'(scheduled {p50_s:.1f} ms, {speedup}x)', flush=True)
        print(f'  quality        : mean mIoU vs reference '
              f'{delta["mean_miou"]:.4f} (min {delta["min_miou"]:.4f}) '
              f'over {delta["frames_compared"]} frames | consistency '
              f'{steady.get("consistency")}', flush=True)
        if delta['frames_compared'] == 0:
            problems.append('quality pass compared 0 frames '
                            '(mask collection broke)')
        if args.min_miou is not None and delta['mean_miou'] is not None \
                and delta['mean_miou'] < args.min_miou:
            problems.append(f'scheduled-vs-reference mean mIoU '
                            f'{delta["mean_miou"]} < --min-miou '
                            f'{args.min_miou}')
        if args.min_speedup is not None and speedup is not None \
                and speedup < args.min_speedup:
            problems.append(f'frame p50 speedup {speedup}x < '
                            f'--min-speedup {args.min_speedup}x')
        if reference.get('errors') or reference.get('rejected'):
            problems.append(
                f'reference pass saw failures: '
                f'{reference.get("errors")} errors, '
                f'{reference.get("rejected")} rejected')

        # ---- retrace gate: the session plane must never grow the
        # sealed executable table — per-session bucket pinning is the
        # zero-retrace mechanism, this is its measurement
        engines = _replica_engine_stats(replicas)
        report['engines'] = engines
        retraces = sum(e['retraces'] or 0 for e in engines.values())
        if retraces:
            problems.append(f'{retraces} retraces across the fleet '
                            f'(want 0)')
        print(f'  engines        : '
              + ' '.join(f'{rid} retraces={e["retraces"]} '
                         f'executables={e["executables"]}'
                         for rid, e in sorted(engines.items())),
              flush=True)
    finally:
        if router is not None:
            router.shutdown()
        manager.stop(drain=False)
        sink.emit({'event': 'run_end'})
        sink.close()
        if obs.get_sink() is sink:
            obs.set_sink(None)

    # ---- sink story: the router must have emitted the migration
    events = _sink_events(obs_dir)
    migrations = [e for e in events
                  if e.get('event') == 'session_migrate']
    report['session_migrate_events'] = len(migrations)
    report['wall_s'] = round(time.perf_counter() - t_start, 1)
    print(f'  sink           : {len(migrations)} session_migrate '
          f'event(s) ({obs_dir})', flush=True)
    if not migrations:
        problems.append('no session_migrate event reached the sink')
    if args.report_json:
        with open(args.report_json, 'w') as f:
            json.dump(report, f, indent=2)
    if args.json:
        print(json.dumps(report, indent=2))
    if args.check:
        if problems:
            print('segstream check FAILED: ' + '; '.join(problems),
                  file=sys.stderr, flush=True)
            return 1
        q = report['quality']
        print(f'segstream check OK: {args.sessions} sessions x '
              f'{args.frames} frames | steady '
              f'{report["steady"]["ok"]} ok, keyframe ratio '
              f'{report["steady"]["keyframe_ratio"]} | kill absorbed '
              f'({report["kill"]["sessions_migrated"]} migrated, 0 '
              f'errors) | exact frame reconciliation | 0 retraces | '
              f'mIoU vs K=1 {q["mean_miou"]} at '
              f'{q["frame_p50_speedup"]}x p50 | {report["wall_s"]}s',
              flush=True)
    return 0


# --------------------------------------------------------------------- main
def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog='segstream', description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest='cmd', required=True)

    bp = sub.add_parser('bench',
                        help='the streaming e2e gate (see docstring)')
    bp.add_argument('--model', default='fastscnn')
    bp.add_argument('--num_class', type=int, default=19)
    bp.add_argument('--compute_dtype', default=None)
    bp.add_argument('--ckpt', default=None)
    bp.add_argument('--buckets', default='64x64',
                    help='session buckets; video payloads use the first')
    bp.add_argument('--batch', type=int, default=4)
    bp.add_argument('--max-wait-ms', type=float, default=2.0)
    bp.add_argument('--max-queue', type=int, default=128)
    bp.add_argument('--workers', type=int, default=2)
    bp.add_argument('--compile-cache', default=None, metavar='DIR')
    bp.add_argument('--replicas', type=int, default=2)
    bp.add_argument('--sessions', type=int, default=4)
    bp.add_argument('--frames', type=int, default=32,
                    help='frames per session per phase')
    bp.add_argument('--fps', type=float, default=10.0,
                    help='per-session frame rate (open loop)')
    bp.add_argument('--keyframe-interval', type=int, default=4)
    bp.add_argument('--cheap-mode', default='reuse',
                    choices=('reuse', 'warp', 'light'))
    bp.add_argument('--frame-deadline-ms', type=float, default=5000.0)
    bp.add_argument('--session-ttl-s', type=float, default=120.0)
    bp.add_argument('--seed', type=int, default=0)
    bp.add_argument('--p99-ms', type=float, default=5000.0)
    bp.add_argument('--max-dropped-late', type=int, default=0,
                    help='steady-phase dropped-late budget')
    bp.add_argument('--max-kill-dropped-late', type=int, default=4,
                    help='kill-phase dropped-late budget (frames in '
                         'flight to the corpse may miss their deadline)')
    bp.add_argument('--keyframe-band-lo', type=float, default=None,
                    help='steady keyframe-ratio gate floor (default '
                         '0.8/K)')
    bp.add_argument('--keyframe-band-hi', type=float, default=None,
                    help='steady keyframe-ratio gate ceiling (default '
                         '1.6/K)')
    bp.add_argument('--min-miou', type=float, default=None,
                    help='gate: scheduled-vs-reference mean mIoU floor')
    bp.add_argument('--min-speedup', type=float, default=None,
                    help='gate: frame p50 speedup floor vs K=1')
    bp.add_argument('--timeout-s', type=float, default=30.0)
    bp.add_argument('--policy', default='least-outstanding',
                    choices=('least-outstanding', 'round-robin'))
    bp.add_argument('--max-outstanding', type=int, default=256)
    bp.add_argument('--port', type=int, default=0)
    bp.add_argument('--run-dir', default=None)
    bp.add_argument('--ready-timeout-s', type=float, default=600.0)
    bp.add_argument('--drain-grace-s', type=float, default=30.0)
    bp.add_argument('--obs-dir', default=None)
    bp.add_argument('--json', action='store_true')
    bp.add_argument('--report-json', default=None, metavar='PATH')
    bp.add_argument('--check', action='store_true')

    args = ap.parse_args(argv)
    return cmd_bench(args)


if __name__ == '__main__':
    sys.exit(main())
