#!/usr/bin/env python
"""segwarm — compile-cache management CLI (rtseg_tpu/warm/).

Prebuild, inspect, and clear the persistent compile caches that give
trainer launches and ServeEngine inits zero-compile warm starts.

Usage:
    # pre-bake serve executables for a deploy (run on the target topology;
    # pass the SAME --ckpt the replicas will serve — the executable embeds
    # the weights, so a random-init prebake only warms load-gen engines)
    python tools/segwarm.py warm --cache-dir /ssd/segwarm \
        --models fastscnn,bisenetv2 --buckets 512x1024,256x512 --batch 8 \
        --ckpt save/best.ckpt

    # pre-bake the compiled train+eval steps for a config (or a zoo subset)
    python tools/segwarm.py warm --cache-dir /ssd/segwarm --train \
        --models fastscnn --train-bs 16 --crop 512
    python tools/segwarm.py warm --cache-dir /ssd/segwarm --train \
        --config save/run1/config.json

    # hits, misses, bytes, per-entry provenance, recorded fallbacks
    python tools/segwarm.py stats --cache-dir /ssd/segwarm [--json]
    # CI gate: exit 1 if any load error silently degraded to a compile
    python tools/segwarm.py stats --cache-dir /ssd/segwarm --check

    python tools/segwarm.py clear --cache-dir /ssd/segwarm

Caveats a prebake must respect (all are safe-by-key — a mismatch is a
cache miss, never a stale hit): executables bind the jax/jaxlib versions,
backend, and device topology of the machine that baked them; train-step
entries additionally bind the config's trace-relevant fields (batch/crop
geometry, loss heads, EMA, dtype). Configs using the segpipe raw uint8
tail (device_norm) train through a different step signature than this
tool bakes — their first real run warms the cache instead.

Exit codes: 0 ok, 1 --check failed, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rtseg_tpu.warm import (ExeCache, clear_cache,          # noqa: E402
                            enable_compile_cache, scan_cache)


def _mib(n: int) -> str:
    return f'{n / 2**20:.1f} MiB'


def _build_cfg(args, model: str):
    from rtseg_tpu.config import SegConfig
    cfg = SegConfig(dataset='synthetic', model=model,
                    num_class=args.num_class,
                    compute_dtype=args.compute_dtype,
                    compile_cache=True, compile_cache_dir=args.cache_dir,
                    compile_workers=args.compile_workers,
                    save_dir='/tmp/segwarm_cli', use_tb=False)
    cfg.resolve(num_devices=1)
    return cfg


def _warm_serve(args) -> int:
    """One ServeEngine.from_config per model: the engine builds its own
    ExeCache from the config's compile_cache_dir and its bucket table
    compiles (or deserializes) straight through it."""
    from rtseg_tpu.serve import ServeEngine, parse_buckets
    buckets = parse_buckets(args.buckets)
    n_built = 0
    for model in args.model_list:
        t0 = time.perf_counter()
        engine = ServeEngine.from_config(_build_cfg(args, model), buckets,
                                         args.batch, ckpt_path=args.ckpt,
                                         name=f'warm:{model}')
        st = engine.stats()
        print(f'  {model}: {st["executables"]} bucket executable(s) in '
              f'{time.perf_counter() - t0:.2f} s '
              f'({st["cache_hits"]} already cached)', flush=True)
        n_built += st['executables']
    return n_built


def _warm_train(args, cache: ExeCache) -> int:
    """AOT-lower the compiled train and eval steps exactly as SegTrainer's
    first call would — same mesh, same replicated/batch shardings, same
    pins — and push them through the exe cache without executing a step."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from rtseg_tpu.config import SegConfig
    from rtseg_tpu.models import get_model
    from rtseg_tpu.models.registry import AUX_MODELS, DETAIL_HEAD_MODELS
    from rtseg_tpu.parallel import (batch_sharding, make_global_array,
                                    make_mesh, replicated)
    from rtseg_tpu.train.optim import get_optimizer
    from rtseg_tpu.train.state import create_train_state
    from rtseg_tpu.train.step import build_eval_step, build_train_step
    from rtseg_tpu.warm.prime import step_pins

    configs = []
    if args.config:
        with open(args.config) as f:
            cfg = SegConfig.from_dict(json.load(f))
        cfg.compile_cache, cfg.compile_cache_dir = True, args.cache_dir
        cfg.resolve()
        if cfg.device_norm_resolved or cfg.device_norm:
            # baking anyway would store f32-signature steps the real
            # (uint8 raw-tail) run can never hit — dead entries and a
            # false "prepaid" success; skip and say so
            print('segwarm: skipping this config — it uses the segpipe '
                  'raw uint8 tail (device_norm), whose step signature '
                  'this tool does not bake; let the first real run warm '
                  'the cache instead', flush=True)
            return 0
        configs.append(cfg)
    else:
        for model in args.model_list:
            cfg = _build_cfg(args, model)
            cfg.train_bs, cfg.val_bs = args.train_bs, args.train_bs
            cfg.crop_size = cfg.crop_h = cfg.crop_w = args.crop
            cfg.use_aux = model in AUX_MODELS
            cfg.use_detail_head = model in DETAIL_HEAD_MODELS
            cfg.total_epoch = args.total_epoch
            if args.train_num:
                cfg.train_num = args.train_num
            configs.append(cfg)

    mesh = make_mesh(spatial_partition=configs[0].spatial_partition)
    n_dev = int(mesh.devices.size)
    n_built = 0
    for cfg in configs:
        cfg.resolve(num_devices=n_dev)
        # the LR schedule (and the EMA ramp) bake total_itrs into the
        # train-step program, so the baked schedule must reproduce the
        # target run's: a saved config carries its resolved train_num;
        # zoo mode takes --train-num/--total-epoch (a mismatch is a safe
        # cache miss, not a stale hit)
        cfg.resolve_schedule(train_num=cfg.train_num
                             or cfg.train_bs * n_dev)
        t0 = time.perf_counter()
        model = get_model(cfg)
        optimizer = get_optimizer(cfg)
        state = jax.device_put(
            create_train_state(model, optimizer, jax.random.PRNGKey(
                cfg.random_seed),
                jnp.zeros((1, cfg.crop_h, cfg.crop_w, 3), jnp.float32)),
            replicated(mesh))
        bsh = batch_sharding(mesh)

        def batch(per_dev_bs):
            gb = per_dev_bs * n_dev
            return (make_global_array(
                np.zeros((gb, cfg.crop_h, cfg.crop_w, 3), np.float32),
                bsh),
                make_global_array(
                np.zeros((gb, cfg.crop_h, cfg.crop_w), np.int32), bsh))

        imgs, msks = batch(cfg.train_bs)
        vimgs, vmsks = ((imgs, msks) if cfg.val_bs == cfg.train_bs
                        else batch(cfg.val_bs))
        train_step = build_train_step(cfg, model, optimizer, mesh)
        eval_step = build_eval_step(cfg, model, mesh)
        hits = 0
        for step, name, a in ((train_step, 'train_step',
                               (state, imgs, msks)),
                              (eval_step, 'eval_step',
                               (state, vimgs, vmsks))):
            step.pin()
            _, hit = cache.load_or_compile(step.jitted.lower(*a),
                                           name=name,
                                           pins=step_pins(step))
            hits += int(hit)
            n_built += 1
        print(f'  {cfg.model}: train+eval steps '
              f'(bs{cfg.train_bs}x{n_dev}, {cfg.crop_h}x{cfg.crop_w}) in '
              f'{time.perf_counter() - t0:.2f} s ({hits} already cached)',
              flush=True)
    return n_built


def cmd_warm(args) -> int:
    args.model_list = [m.strip() for m in args.models.split(',')
                       if m.strip()]
    if not args.model_list and not args.config:
        print('segwarm: warm needs --models or --config', file=sys.stderr)
        return 2
    if args.config:
        # a saved config always means the train/eval steps — without this,
        # --config alone would fall into serve mode's empty model loop and
        # "succeed" having baked nothing
        args.train = True
    enable_compile_cache(cache_dir=args.cache_dir)
    before = scan_cache(args.cache_dir)
    t0 = time.perf_counter()
    if args.train:
        n = _warm_train(args, ExeCache.at(args.cache_dir))
    else:
        n = _warm_serve(args)
    # deltas from the on-disk provenance: serve mode compiles through the
    # engine's own cache instance, so in-process counters would undercount
    after = scan_cache(args.cache_dir)
    print(f'segwarm: {n} executable(s) warm under {args.cache_dir} in '
          f'{time.perf_counter() - t0:.2f} s — '
          f'{after["n_entries"] - before["n_entries"]} compiled + stored '
          f'({_mib(after["bytes"] - before["bytes"])}), '
          f'{after["hits"] - before["hits"]} already cached, '
          f'{after["n_fallbacks"] - before["n_fallbacks"]} fallback(s)',
          flush=True)
    return 0


def cmd_stats(args) -> int:
    s = scan_cache(args.cache_dir)
    if args.json:
        print(json.dumps(s, indent=2, default=str))
    else:
        print(f'segwarm stats — {s["cache_dir"]}')
        print(f'  exe entries : {s["n_entries"]} | {_mib(s["bytes"])} | '
              f'{s["hits"]} recorded hit(s)')
        print(f'  xla entries : {s["xla_entries"]} | '
              f'{_mib(s["xla_bytes"])} (persistent XLA cache)')
        print(f'  fallbacks   : {s["n_fallbacks"]}')
        for e in s['entries']:
            print(f'    {e.get("name", "?"):<24} key={e.get("key", "?")[:12]}'
                  f'… {_mib(int(e.get("bytes", 0)))} compile '
                  f'{e.get("compile_s", 0.0):.2f}s hits '
                  f'{e.get("hits", 0)} (jax {e.get("jax", "?")}, '
                  f'{e.get("platform", "?")} x{e.get("n_devices", "?")})')
        for fb in s['fallbacks']:
            print(f'    FALLBACK {fb.get("name", "?")} '
                  f'key={fb.get("key", "?")[:12]}… {fb.get("error", "")}')
    if args.check:
        problems = []
        if s['n_fallbacks']:
            problems.append(f'{s["n_fallbacks"]} cached executable(s) '
                            f'failed to load and fell back to a fresh '
                            f'compile (see fallbacks above)')
        if args.min_entries and s['n_entries'] < args.min_entries:
            problems.append(f'{s["n_entries"]} entries < --min-entries '
                            f'{args.min_entries}')
        if args.min_hits and s['hits'] < args.min_hits:
            problems.append(f'{s["hits"]} recorded hits < --min-hits '
                            f'{args.min_hits}')
        if problems:
            print('segwarm check FAILED: ' + '; '.join(problems),
                  file=sys.stderr)
            return 1
        print(f'segwarm check OK: {s["n_entries"]} entries, {s["hits"]} '
              f'hits, 0 fallbacks')
    return 0


def cmd_clear(args) -> int:
    n = clear_cache(args.cache_dir)
    print(f'segwarm: removed {n} cached file(s) under {args.cache_dir}')
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog='segwarm', description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest='cmd', required=True)

    wp = sub.add_parser('warm', help='prebuild compile caches')
    wp.add_argument('--cache-dir', required=True)
    wp.add_argument('--models', default='',
                    help='comma-separated zoo subset')
    wp.add_argument('--num_class', type=int, default=19)
    wp.add_argument('--compute_dtype', default=None)
    wp.add_argument('--compile-workers', type=int, default=0)
    wp.add_argument('--buckets', default='512x1024',
                    help='serve mode: HxW bucket list')
    wp.add_argument('--batch', type=int, default=8,
                    help='serve mode: per-executable batch')
    wp.add_argument('--ckpt', default=None,
                    help='serve mode: checkpoint the replicas will serve')
    wp.add_argument('--train', action='store_true',
                    help='bake compiled train+eval steps instead of serve '
                         'buckets')
    wp.add_argument('--config', default=None,
                    help='--train: a saved config.json to bake exactly')
    wp.add_argument('--train-bs', type=int, default=16,
                    help='--train zoo mode: per-device batch')
    wp.add_argument('--crop', type=int, default=512,
                    help='--train zoo mode: crop size')
    wp.add_argument('--total-epoch', type=int, default=200,
                    help='--train zoo mode: schedule epochs (baked into '
                         'the train-step LR schedule — must match the '
                         'target run)')
    wp.add_argument('--train-num', type=int, default=0,
                    help='--train zoo mode: dataset length for the '
                         'schedule (0 = one global batch)')

    st = sub.add_parser('stats', help='cache contents and provenance')
    st.add_argument('--cache-dir', required=True)
    st.add_argument('--json', action='store_true')
    st.add_argument('--check', action='store_true',
                    help='exit 1 on any recorded fallback (plus optional '
                         '--min-entries/--min-hits floors)')
    st.add_argument('--min-entries', type=int, default=0)
    st.add_argument('--min-hits', type=int, default=0)

    cp = sub.add_parser('clear', help='delete all cached artifacts')
    cp.add_argument('--cache-dir', required=True)

    args = ap.parse_args(argv)
    if args.cmd == 'warm':
        return cmd_warm(args)
    if args.cmd == 'stats':
        return cmd_stats(args)
    return cmd_clear(args)


if __name__ == '__main__':
    sys.exit(main())
