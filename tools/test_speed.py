"""FPS benchmark — TPU-native equivalent of reference tools/test_speed.py:9-61.

jit'd forward on the configured model, `block_until_ready` fencing replacing
torch.cuda.synchronize, same warmup (10 iters) + auto-calibration (~6s worth)
protocol. Reports latency (ms) and FPS at bs1 plus batched imgs/sec (the
TPU-relevant throughput number).

First-call compile time is reported as its own labeled line, never folded
into the steady-state numbers: `--cold` (default) measures a fresh XLA
compile, `--warm` compiles through the segwarm executable cache at
`--warm-cache DIR` (first run stores, later runs deserialize) — so a
"model loads in N ms" claim is always labeled with which path produced it.
"""

import sys
import time
from os import path

sys.path.append(path.dirname(path.dirname(path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from rtseg_tpu.config import SegConfig, load_parser
from rtseg_tpu.models import get_model


def test_model_speed(config, ratio=0.5, imgw=2048, imgh=1024,
                     iterations=None, batch_size=1, warm_cache=None):
    if ratio != 1.0:
        assert ratio > 0, 'Ratio should be larger than 0.'
        imgw = int(imgw * ratio)
        imgh = int(imgh * ratio)

    model = get_model(config)
    print('\n=========Speed Testing=========')
    print(f'Model: {config.model}\nEncoder: {config.encoder}\n'
          f'Decoder: {config.decoder}')
    print(f'Size (W, H): {imgw}, {imgh} | batch: {batch_size}')

    x = jnp.asarray(np.random.randn(batch_size, imgh, imgw, 3)
                    .astype(np.float32))
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, imgh, imgw, 3)), False)

    dtype = jnp.dtype(config.compute_dtype)

    @jax.jit
    def fwd(variables, x):
        return model.apply(variables, x.astype(dtype), False)

    # first-call compile, timed on its own — startup cost must never hide
    # inside (or be hidden by) the steady-state FPS protocol below. The
    # AOT-compiled executable is then what every timed call dispatches to.
    from rtseg_tpu.warm import make_pins, timed_compile
    compiled, compile_s, label = timed_compile(
        fwd.lower(variables, x),
        f'{config.model} fwd {imgw}x{imgh} bs{batch_size}',
        cache=warm_cache,
        pins=make_pins(bn_axis=None,
                       s2d_stem=bool(getattr(config, 's2d_stem', False)),
                       defer_upsample=False))
    print(f'First-call compile: {compile_s:.3f} s ({label})')

    def fwd(variables, x, _c=compiled):      # noqa: F811 — AOT dispatch
        return _c(variables, x)

    for _ in range(10):                      # warmup
        jax.block_until_ready(fwd(variables, x))

    if iterations is None:
        elapsed = 0.0
        iterations = 100
        while elapsed < 1:
            t0 = time.time()
            for _ in range(iterations):
                out = fwd(variables, x)
            jax.block_until_ready(out)
            elapsed = time.time() - t0
            iterations *= 2
        fps = iterations / elapsed
        iterations = int(fps * 6)

    t0 = time.time()
    for _ in range(iterations):
        out = fwd(variables, x)
    jax.block_until_ready(out)
    elapsed = time.time() - t0
    latency = elapsed / iterations * 1000
    fps = 1000 / latency

    # Per-call synchronized latency distribution: the pipelined loop above
    # yields a throughput mean, which hides the tail — and the tail (p95+)
    # is what a serving SLO actually gates on (BENCHMARKS.md "Serving
    # latency methodology"). Each call here is fenced individually, so the
    # percentiles are true per-call latencies, not async dispatch times.
    lat_iters = min(int(iterations), 200)
    lats = np.empty(lat_iters, np.float64)
    for i in range(lat_iters):
        t0 = time.time()
        jax.block_until_ready(fwd(variables, x))
        lats[i] = time.time() - t0
    p50, p95 = np.percentile(lats * 1000, [50, 95])
    print(f'Latency: {latency:.3f} ms mean (pipelined) | '
          f'p50 {p50:.3f} ms / p95 {p95:.3f} ms (per-call, fenced, '
          f'n={lat_iters}) | FPS: {fps:.1f} | '
          f'imgs/sec: {fps * batch_size:.1f}\n')
    return fps


def test_quant_speed(config, ratio=0.5, imgw=2048, imgh=1024,
                     iterations=None, batch_size=1, warm_cache=None):
    """--quant int8: the serving program (argmax head, what a bundle
    ships) timed f32 vs segquant int8 under the same warmup +
    auto-calibration + fenced protocol, plus serialized artifact bytes
    and argmax agreement on the bench batch — side by side."""
    from rtseg_tpu.export import build_inference_fn
    from rtseg_tpu.quant import (build_quantized_inference_fn,
                                 quantize_variables)
    from rtseg_tpu.warm import timed_compile

    if ratio != 1.0:
        assert ratio > 0, 'Ratio should be larger than 0.'
        imgw = int(imgw * ratio)
        imgh = int(imgh * ratio)

    model = get_model(config)
    print('\n=========Quantized Speed Testing (segquant int8)=========')
    print(f'Model: {config.model}\nSize (W, H): {imgw}, {imgh} | '
          f'batch: {batch_size}')

    x = jnp.asarray(np.random.randn(batch_size, imgh, imgw, 3)
                    .astype(np.float32))
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, imgh, imgw, 3)), False)
    qvariables = quantize_variables(variables)
    spec = jax.ShapeDtypeStruct((batch_size, imgh, imgw, 3), jnp.float32)

    def measure(fwd, iters):
        for _ in range(10):                  # warmup
            jax.block_until_ready(fwd(x))
        if iters is None:                    # auto-calibrate ~6s worth
            elapsed, iters = 0.0, 100
            while elapsed < 1:
                t0 = time.time()
                for _ in range(iters):
                    out = fwd(x)
                jax.block_until_ready(out)
                elapsed = time.time() - t0
                iters *= 2
            iters = int(iters / elapsed * 6)
        t0 = time.time()
        for _ in range(iters):
            out = fwd(x)
        jax.block_until_ready(out)
        return 1000 / ((time.time() - t0) / iters * 1000), iters

    rows = {}
    preds = {}
    for arm, fn in (('f32', build_inference_fn(
                        model, variables, config.compute_dtype,
                        argmax=True)),
                    ('int8', build_quantized_inference_fn(
                        model, qvariables, config.compute_dtype,
                        argmax=True))):
        compiled, compile_s, label = timed_compile(
            jax.jit(fn).lower(x),
            f'{config.model} {arm} serve {imgw}x{imgh} bs{batch_size}',
            cache=warm_cache)
        print(f'{arm} first-call compile: {compile_s:.3f} s ({label})')
        fps, iterations = measure(compiled, iterations)
        art = len(jax.export.export(jax.jit(fn))(spec).serialize())
        preds[arm] = np.asarray(compiled(x))
        rows[arm] = (fps, art)
    agree = float((preds['f32'] == preds['int8']).mean())
    print(f'\n| arm | FPS | imgs/sec | artifact (MiB) |')
    print('|---|---|---|---|')
    for arm in ('f32', 'int8'):
        fps, art = rows[arm]
        print(f'| {arm} | {fps:.1f} | {fps * batch_size:.1f} | '
              f'{art / 2**20:.2f} |')
    print(f'\nint8/f32 throughput: '
          f'{rows["int8"][0] / rows["f32"][0]:.2f}x | artifact shrink: '
          f'{rows["f32"][1] / rows["int8"][1]:.2f}x | argmax agreement: '
          f'{agree:.4f} (random-init weights, bench batch)\n')
    return rows['int8'][0]


def _pop_warm_args(argv):
    """Split the --cold/--warm toggle (--warm-cache DIR, --quant int8)
    out of argv before the SegConfig parser sees the rest."""
    import argparse
    pre = argparse.ArgumentParser(add_help=False, allow_abbrev=False)
    grp = pre.add_mutually_exclusive_group()
    grp.add_argument('--warm', action='store_true')
    grp.add_argument('--cold', action='store_true')
    pre.add_argument('--warm-cache', default='/tmp/rtseg_bench/segwarm')
    pre.add_argument('--quant', choices=('int8',), default=None)
    ns, rest = pre.parse_known_args(argv)
    return ns.warm, ns.warm_cache, ns.quant, rest


if __name__ == '__main__':
    warm, cache_dir, quant, rest = _pop_warm_args(sys.argv[1:])
    config = SegConfig(dataset='synthetic', model='bisenetv2', num_class=19)
    if rest:
        config = load_parser(config, rest)
    config.resolve(num_devices=1)
    warm_cache = None
    if warm:
        from rtseg_tpu.warm import ExeCache, enable_compile_cache
        enable_compile_cache(cache_dir=cache_dir)
        warm_cache = ExeCache.at(cache_dir)
    if quant:
        test_quant_speed(config, warm_cache=warm_cache)
    else:
        test_model_speed(config, warm_cache=warm_cache)
