"""FPS benchmark — TPU-native equivalent of reference tools/test_speed.py:9-61.

jit'd forward on the configured model, `block_until_ready` fencing replacing
torch.cuda.synchronize, same warmup (10 iters) + auto-calibration (~6s worth)
protocol. Reports latency (ms) and FPS at bs1 plus batched imgs/sec (the
TPU-relevant throughput number).
"""

import sys
import time
from os import path

sys.path.append(path.dirname(path.dirname(path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from rtseg_tpu.config import SegConfig, load_parser
from rtseg_tpu.models import get_model


def test_model_speed(config, ratio=0.5, imgw=2048, imgh=1024,
                     iterations=None, batch_size=1):
    if ratio != 1.0:
        assert ratio > 0, 'Ratio should be larger than 0.'
        imgw = int(imgw * ratio)
        imgh = int(imgh * ratio)

    model = get_model(config)
    print('\n=========Speed Testing=========')
    print(f'Model: {config.model}\nEncoder: {config.encoder}\n'
          f'Decoder: {config.decoder}')
    print(f'Size (W, H): {imgw}, {imgh} | batch: {batch_size}')

    x = jnp.asarray(np.random.randn(batch_size, imgh, imgw, 3)
                    .astype(np.float32))
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, imgh, imgw, 3)), False)

    dtype = jnp.dtype(config.compute_dtype)

    @jax.jit
    def fwd(variables, x):
        return model.apply(variables, x.astype(dtype), False)

    for _ in range(10):                      # warmup + compile
        jax.block_until_ready(fwd(variables, x))

    if iterations is None:
        elapsed = 0.0
        iterations = 100
        while elapsed < 1:
            t0 = time.time()
            for _ in range(iterations):
                out = fwd(variables, x)
            jax.block_until_ready(out)
            elapsed = time.time() - t0
            iterations *= 2
        fps = iterations / elapsed
        iterations = int(fps * 6)

    t0 = time.time()
    for _ in range(iterations):
        out = fwd(variables, x)
    jax.block_until_ready(out)
    elapsed = time.time() - t0
    latency = elapsed / iterations * 1000
    fps = 1000 / latency

    # Per-call synchronized latency distribution: the pipelined loop above
    # yields a throughput mean, which hides the tail — and the tail (p95+)
    # is what a serving SLO actually gates on (BENCHMARKS.md "Serving
    # latency methodology"). Each call here is fenced individually, so the
    # percentiles are true per-call latencies, not async dispatch times.
    lat_iters = min(int(iterations), 200)
    lats = np.empty(lat_iters, np.float64)
    for i in range(lat_iters):
        t0 = time.time()
        jax.block_until_ready(fwd(variables, x))
        lats[i] = time.time() - t0
    p50, p95 = np.percentile(lats * 1000, [50, 95])
    print(f'Latency: {latency:.3f} ms mean (pipelined) | '
          f'p50 {p50:.3f} ms / p95 {p95:.3f} ms (per-call, fenced, '
          f'n={lat_iters}) | FPS: {fps:.1f} | '
          f'imgs/sec: {fps * batch_size:.1f}\n')
    return fps


if __name__ == '__main__':
    config = SegConfig(dataset='synthetic', model='bisenetv2', num_class=19)
    if len(sys.argv) > 1:
        config = load_parser(config)
    config.resolve(num_devices=1)
    test_model_speed(config)
